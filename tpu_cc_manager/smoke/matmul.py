"""Matmul smoke workload: prove the slice multiplies correctly and fast.

BASELINE.json configs[1] ("libtpu CC toggle + JAX matmul smoke test").
TPU-first design notes:

- bf16 operands, f32 accumulation (``preferred_element_type``) — the MXU's
  native contraction;
- square tiles sized to keep the MXU busy (4096 on accelerators, small on
  CPU test runs);
- sharded over all visible devices with a 1-D mesh so the same code
  exercises 1 chip or a full slice (collectives ride ICI via XLA);
- numerics oracle: a deterministic low-rank construction whose product is
  known in closed form, checked with bf16-appropriate tolerance, plus a
  f64-free checksum — no host-side reference matmul at full size.
"""

from __future__ import annotations

import time
from functools import partial


def run(size: int | None = None, iters: int = 8, seed: int = 0,
        kernel: str = "xla") -> dict:
    """kernel='xla' uses jnp.matmul (stock compiler); kernel='pallas' uses
    the Mosaic tiled kernel (ops/matmul.py) — single-device only, used to
    prove custom-kernel compilation works on a reconfigured slice."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devices = jax.devices()
    backend = jax.default_backend()
    if kernel == "pallas":
        devices = devices[:1]  # the Mosaic kernel is single-device
    if size is None:
        size = 4096 if backend == "tpu" else 256
    # Round to a multiple of (128 * device count) — keeps every shard aligned
    # to the MXU/VPU lane width after sharding.
    n_dev = len(devices)
    size = max(128 * n_dev, (size // (128 * n_dev)) * (128 * n_dev))

    key = jax.random.PRNGKey(seed)
    k1, k2 = jax.random.split(key)
    a = jax.random.normal(k1, (size, size), dtype=jnp.bfloat16)
    b = jax.random.normal(k2, (size, size), dtype=jnp.bfloat16)

    mesh = Mesh(devices, ("x",))
    row_sharding = NamedSharding(mesh, P("x", None))
    repl = NamedSharding(mesh, P())
    a = jax.device_put(a, row_sharding)
    b = jax.device_put(b, repl)

    if kernel == "pallas":
        from tpu_cc_manager.ops.matmul import tiled_matmul

        block = 512 if size % 512 == 0 else 128

        @jax.jit
        def mm(a, b):
            return tiled_matmul(a, b, block_m=block, block_n=block, block_k=block)

    else:

        @partial(jax.jit, out_shardings=row_sharding)
        def mm(a, b):
            return jnp.matmul(a, b, preferred_element_type=jnp.float32)

    # Warmup/compile.
    out = mm(a, b)
    out.block_until_ready()

    t0 = time.perf_counter()
    for _ in range(iters):
        out = mm(a, b)
    out.block_until_ready()
    dt = (time.perf_counter() - t0) / iters
    tflops = 2 * size**3 / dt / 1e12

    # Numerics: identity sanity (A @ I == A within bf16 cast error) plus a
    # row-sum cross-check of the measured product: out @ 1 == A @ (B @ 1).
    eye = jax.device_put(jnp.eye(size, dtype=jnp.bfloat16), repl)
    ident = mm(a, eye)
    ident_err = float(jnp.max(jnp.abs(ident - a.astype(jnp.float32))))
    ones = jnp.ones((size, 1), dtype=jnp.float32)
    lhs = jnp.matmul(out, ones)
    rhs = jnp.matmul(a.astype(jnp.float32), jnp.matmul(b.astype(jnp.float32), ones))
    scale = float(jnp.max(jnp.abs(rhs))) + 1e-6
    rowsum_rel_err = float(jnp.max(jnp.abs(lhs - rhs))) / scale
    # bf16 has ~8 mantissa bits; row-sum of `size` products loses a few more.
    ok = ident_err <= 1e-6 and rowsum_rel_err <= 2e-2

    return {
        "ok": bool(ok),
        "workload": "matmul",
        "kernel": kernel,
        "backend": backend,
        "devices": n_dev,
        "size": size,
        "seconds_per_iter": dt,
        "tflops": round(tflops, 2),
        "ident_err": ident_err,
        "rowsum_rel_err": rowsum_rel_err,
    }


if __name__ == "__main__":
    import json

    print(json.dumps(run()))
