"""JAX/XLA smoke workloads: end-to-end validation of a reconfigured slice.

New subsystem with no reference counterpart (SURVEY.md §0(d), §3.4): the
reference's verify phase stops at ``query_cc_mode() == mode``; here each
reconfigure can additionally prove the slice does real, numerically correct
work by running one of these workloads (selected via --smoke-workload):

- ``matmul``  bf16 MXU matmul + numerics check (BASELINE.json configs[1]),
- ``llama``   Llama decode microbenchmark, tokens/sec (configs[2], [4]),
- ``resnet``  ResNet-50 train step, MFU (configs[3]).

Workloads run in a subprocess (``python -m tpu_cc_manager.smoke``) so the
manager process never holds the TPU.
"""
