"""Subprocess entry for smoke workloads: prints one JSON result line last."""

from __future__ import annotations

import argparse
import json
import sys


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="tpu_cc_manager.smoke")
    p.add_argument("--workload", required=True)
    p.add_argument("--size", default=None,
                   help="problem-size override: an integer for matmul, a "
                   "named config for llama/resnet (e.g. tiny, 500m, "
                   "llama2-7b, resnet50)")
    p.add_argument("--kernel", default=None, choices=["xla", "pallas"],
                   help="matmul only: 'pallas' runs the Mosaic tiled kernel "
                   "(ops/matmul.py) to prove custom-kernel compilation on a "
                   "reconfigured slice")
    p.add_argument("--pallas-blocks", default=None, metavar="M,N,K",
                   help="matmul+pallas only: tiling override for one-command "
                   "on-chip tuning sweeps (e.g. 512,512,1024)")
    p.add_argument("--batch", type=int, default=None,
                   help="llama/resnet only: global batch override (MFU/"
                   "throughput tuning; resnet MFU in particular scales "
                   "with batch until HBM runs out)")
    p.add_argument("--profile-dir", default=None,
                   help="capture a JAX profiler trace of the workload into "
                   "this directory (open with tensorboard/xprof; the "
                   "MFU-accounting companion when a number looks off)")
    args = p.parse_args(argv)

    # Before any jax import: persistent XLA cache makes every verify run
    # after the first compile-free (see utils/compilation_cache.py).
    from tpu_cc_manager.utils.compilation_cache import enable

    enable()

    from tpu_cc_manager.smoke.runner import SmokeError, run_workload

    def usage_error(message: str) -> int:
        # Same one-JSON-line shape as SmokeConfigError failures.
        print(json.dumps({
            "ok": False, "workload": args.workload, "error": message,
        }))
        return 1

    kwargs = {}
    if args.size is not None:
        kwargs["size"] = int(args.size) if args.size.isdigit() else args.size
    if args.kernel is not None:
        if args.workload != "matmul":
            return usage_error("--kernel only applies to the matmul workload")
        kwargs["kernel"] = args.kernel
    if args.batch is not None:
        if args.workload not in ("llama", "resnet"):
            return usage_error(
                "--batch only applies to the llama/resnet workloads"
            )
        if args.batch < 1:
            return usage_error(f"--batch must be positive (got {args.batch})")
        kwargs["batch"] = args.batch
    if args.pallas_blocks is not None:
        if args.kernel != "pallas" or args.workload != "matmul":
            return usage_error(
                "--pallas-blocks requires --workload matmul --kernel pallas"
            )
        try:
            bm, bn, bk = (int(x) for x in args.pallas_blocks.split(","))
        except ValueError:
            return usage_error(
                f"unparseable --pallas-blocks {args.pallas_blocks!r}"
            )
        kwargs["blocks"] = (bm, bn, bk)
    try:
        if args.profile_dir:
            import jax

            with jax.profiler.trace(args.profile_dir):
                result = run_workload(args.workload, **kwargs)
        else:
            result = run_workload(args.workload, **kwargs)
    except SmokeError as e:
        # Covers workload failure AND bad parameters (SmokeConfigError:
        # unknown sizes, non-dividing pallas blocks) — the one-JSON-line
        # stdout contract holds for misconfigured sweeps, while genuine
        # runtime defects (e.g. a JAX ValueError) keep their tracebacks.
        print(json.dumps({"ok": False, "workload": args.workload, "error": str(e)}))
        return 1
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
