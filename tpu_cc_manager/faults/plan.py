"""Seeded fault plans: reproducible chaos schedules.

A fault plan is a pure function of (seed, call sequence): every decision
draws from one ``random.Random(seed)``, so running the same operations
against the same plan yields the same injected faults — the property the
chaos-seed reproduction test (tests/test_chaos.py) locks in. The seed
comes from ``CC_CHAOS_SEED`` so a soak failure in CI is replayable on a
laptop with one env var.

``max_faults`` bounds the total injections; a converging system must
eventually see clean weather, and the soak asserts convergence AFTER the
fault budget runs dry.
"""

from __future__ import annotations

import os
import random
from dataclasses import dataclass, field

CHAOS_SEED_ENV = "CC_CHAOS_SEED"


class OrchestratorKilled(BaseException):
    """A seeded SIGKILL of the rolling orchestrator (FaultPlan
    ``decide_orchestrator_kill``). Derives from BaseException so no
    except-Exception cleanup path in the orchestrator can swallow it —
    the whole point is modeling a death that runs NO handlers: the lease
    is not released, the record not finalized, and the successor must
    recover from exactly what was durably checkpointed."""

    def __init__(self, point: str, seq: int):
        super().__init__(f"orchestrator killed at {point} (seq={seq})")
        self.point = point
        self.seq = seq

#: Fault kinds the kube wrapper understands.
KINDS = (
    "http-429",      # throttled, with a Retry-After header
    "http-5xx",      # transient server error (500/502/503/504)
    "conn-reset",    # transport-level failure (status=None)
    "slow",          # response delayed by ``slow_s``
)
WATCH_KINDS = (
    "watch-hangup",  # stream dies mid-flight with a transport error
    "stale-rv",      # 410 Gone on connect (forces the resync path)
)
#: Total-outage mode (``blackout_rate``): while a window is open EVERY
#: verb — watch connects included — refuses with a connection reset, the
#: signature of a dead apiserver/load balancer. This is the fault the
#: disconnected-mode ladder (ccmanager/intent_journal.py) exists for.
BLACKOUT_KIND = "blackout"


@dataclass(frozen=True)
class Fault:
    kind: str
    op: str
    seq: int                      # decision index within the plan
    status: int | None = None
    retry_after_s: float | None = None
    slow_s: float | None = None

    def describe(self) -> str:
        extra = ""
        if self.status is not None:
            extra = f" status={self.status}"
        if self.retry_after_s is not None:
            extra += f" retry_after={self.retry_after_s}"
        return f"{self.kind} on {self.op} (seq={self.seq}{extra})"


@dataclass
class FaultPlan:
    """Draws one decision per API call; deterministic given the seed."""

    seed: int = 0
    # Probability an eligible call gets a fault (split evenly over kinds).
    rate: float = 0.2
    watch_rate: float = 0.3
    # Probability a crash point kills the orchestrator (0 = kill mode off;
    # decide_orchestrator_kill). Separate from ``rate``: orchestrator
    # deaths are rare catastrophic events, not per-call weather.
    kill_rate: float = 0.0
    max_kills: int | None = None
    max_faults: int | None = None
    retry_after_s: float = 0.05
    slow_s: float = 0.02
    # Apiserver-blackout mode: probability an eligible call STARTS a total-
    # outage window (0 disables), and the window's length in API calls,
    # drawn uniformly from [blackout_min_calls, blackout_max_calls]. The
    # windows are seeded — a DERIVED stream, so enabling blackouts does not
    # reshuffle the per-call fault schedule other modes draw from the main
    # stream — and each whole window counts ONCE against max_faults.
    blackout_rate: float = 0.0
    blackout_min_calls: int = 5
    blackout_max_calls: int = 20
    max_blackouts: int | None = None
    # Preemption-notice mode (spot/preemptible churn): probability that
    # schedule_preemption arms a platform preemption notice on the fake
    # backend, and the hard termination deadline the scenario models —
    # deliberately FAR below the 300 s drain budget (GCE gives ~30 s),
    # which is the whole point: the normal drain cannot finish, the
    # fast-drain path (drain/evict.py) must.
    preemption_rate: float = 0.0
    preemption_deadline_s: float = 30.0
    # Per-verb slow latency overrides: when the drawn kind is ``slow``,
    # the delay for ``op`` comes from here (falling back to the global
    # ``slow_s``). Consulted with ZERO extra rng draws, so arming
    # per-verb weather composes with existing chaos seeds without
    # reshuffling the schedule other modes draw from the main stream.
    slow_s_by_op: dict[str, float] = field(default_factory=dict)
    # Brownout mode (gray failure, Huang HotOS'17): a SEEDED node fails
    # SLOW, not stop — its executor token rate degrades by
    # ``brownout_token_rate_factor``, its per-chip reset/boot walls
    # inflate by ``brownout_reset_factor``, and its kube ops/probes go
    # intermittently slow (``brownout_kube_slow_rate`` of calls sleep
    # ``brownout_kube_slow_s``) while still SUCCEEDING — the watchdog
    # stays green by construction. Per-call slowness draws from a
    # DERIVED stream so arming a brownout never reshuffles the per-call
    # fault schedule, and the intermittent delays are weather, not
    # budget: they do not count against ``max_faults``.
    brownout_token_rate_factor: float = 4.0
    brownout_reset_factor: float = 3.0
    brownout_kube_slow_rate: float = 0.35
    brownout_kube_slow_s: float = 0.2
    rng: random.Random = field(init=False, repr=False)
    injected: list[Fault] = field(init=False, repr=False)
    _seq: int = field(init=False, repr=False)

    def __post_init__(self) -> None:
        self.rng = random.Random(self.seed)
        self.injected = []
        self._seq = 0
        # Derived, not the main stream (see blackout_rate above).
        self._blackout_rng = random.Random((self.seed << 1) ^ 0x0B1AC0)
        self._blackout_left = 0
        self._forced_blackout = False
        self.blackout_windows = 0
        self.blackout_refusals = 0
        # One-shot forced kill point (seed_prestage_kill): the next
        # decide_orchestrator_kill at exactly this point raises,
        # regardless of kill_rate.
        self._forced_kill_point: str | None = None
        # Derived, not the main stream (see brownout_* above).
        self._brownout_rng = random.Random((self.seed << 2) ^ 0xB70B0)
        self._brownout: int | None = None
        self.brownout_slow_ops = 0

    @classmethod
    def from_env(cls, default_seed: int = 20260803, **kwargs) -> "FaultPlan":
        seed = int(os.environ.get(CHAOS_SEED_ENV, str(default_seed)))
        return cls(seed=seed, **kwargs)

    @property
    def exhausted(self) -> bool:
        return (
            self.max_faults is not None
            and len(self.injected) >= self.max_faults
        )

    def _draw(self, op: str, rate: float, kinds: tuple[str, ...]) -> Fault | None:
        # ALWAYS advance the rng, even when the budget is exhausted — the
        # schedule must stay a pure function of (seed, call sequence), not
        # of how many faults earlier calls happened to absorb.
        self._seq += 1
        roll = self.rng.random()
        kind = kinds[self.rng.randrange(len(kinds))]
        status_5xx = self.rng.choice((500, 502, 503, 504))
        if roll >= rate or self.exhausted:
            return None
        fault = Fault(
            kind=kind,
            op=op,
            seq=self._seq,
            status=(
                429 if kind == "http-429"
                else status_5xx if kind == "http-5xx"
                else 410 if kind == "stale-rv"
                else None
            ),
            retry_after_s=self.retry_after_s if kind == "http-429" else None,
            slow_s=(
                self.slow_s_by_op.get(op, self.slow_s)
                if kind == "slow" else None
            ),
        )
        self.injected.append(fault)
        return fault

    # ---- apiserver-blackout mode ----------------------------------------

    @property
    def in_blackout(self) -> bool:
        return self._forced_blackout or self._blackout_left > 0

    def begin_blackout(self, calls: int | None = None) -> None:
        """Open a total-outage window deterministically (tests and drills):
        ``calls`` bounds it, None keeps it open until :meth:`end_blackout`.
        """
        if calls is None:
            self._forced_blackout = True
        else:
            self._blackout_left = max(self._blackout_left, calls)
        self.blackout_windows += 1

    def end_blackout(self) -> None:
        self._forced_blackout = False
        self._blackout_left = 0

    def _blackout_tick(self, op: str) -> Fault | None:
        """One blackout decision per API call: refuse while a window is
        open, otherwise (blackout_rate > 0) maybe open a seeded one. Both
        draws come from the derived blackout stream on EVERY call, so the
        schedule stays a pure function of (seed, call sequence)."""
        if self.in_blackout:
            if self._blackout_left > 0:
                self._blackout_left -= 1
            self._seq += 1
            self.blackout_refusals += 1
            return Fault(kind=BLACKOUT_KIND, op=op, seq=self._seq)
        if self.blackout_rate <= 0:
            return None
        roll = self._blackout_rng.random()
        span = self._blackout_rng.randint(
            self.blackout_min_calls, max(self.blackout_min_calls,
                                         self.blackout_max_calls)
        )
        if roll >= self.blackout_rate or self.exhausted or (
            self.max_blackouts is not None
            and self.blackout_windows >= self.max_blackouts
        ):
            return None
        self._seq += 1
        self._blackout_left = span - 1  # this call is the first refusal
        self.blackout_windows += 1
        self.blackout_refusals += 1
        fault = Fault(kind=BLACKOUT_KIND, op=op, seq=self._seq)
        self.injected.append(fault)  # the window counts once
        return fault

    def decide(self, op: str) -> Fault | None:
        """One decision for a unary API call."""
        fault = self._blackout_tick(op)
        if fault is not None:
            return fault
        return self._draw(op, self.rate, KINDS)

    def decide_watch(self, op: str = "watch") -> Fault | None:
        """One decision for a watch-stream connect."""
        fault = self._blackout_tick(op)
        if fault is not None:
            return fault
        return self._draw(op, self.watch_rate, WATCH_KINDS)

    def decide_orchestrator_kill(self, point: str) -> None:
        """One decision per orchestrator crash point (window start, mid-
        window, checkpoint boundary): with probability ``kill_rate``,
        raise :class:`OrchestratorKilled` — simulating a SIGKILL landing
        exactly there. Like every decision, drawn from the single seeded
        stream (same seed + same call sequence → the kill lands at the
        same point), and ALWAYS advances the rng even when kill mode is
        off so enabling kills doesn't reshuffle the other faults'
        schedule. ``max_kills`` bounds deaths so a soak's final successor
        gets clean weather to converge in."""
        self._seq += 1
        roll = self.rng.random()
        kills = sum(1 for f in self.injected if f.kind == "orch-kill")
        if self._forced_kill_point == point:
            # Seeded scenario kill (seed_prestage_kill): one-shot, fires
            # at exactly the armed point, bypasses kill_rate but is
            # recorded like a drawn kill. The rng already advanced
            # above, so arming never reshuffles the drawn schedule.
            self._forced_kill_point = None
            fault = Fault(kind="orch-kill", op=point, seq=self._seq)
            self.injected.append(fault)
            raise OrchestratorKilled(point, self._seq)
        if roll >= self.kill_rate or self.exhausted or (
            self.max_kills is not None and kills >= self.max_kills
        ):
            return
        fault = Fault(kind="orch-kill", op=point, seq=self._seq)
        self.injected.append(fault)
        raise OrchestratorKilled(point, self._seq)

    def schedule_journal_fault(self, journal) -> bool:
        """Optionally arm ONE disk fault on the node-local intent journal
        (ccmanager/intent_journal.py ``fail_appends``): the next append
        raises as if the state-dir disk faulted mid-write. Drawn from the
        seeded main stream like the backend faults — the agent must keep
        reconciling (loudly, unjournaled) when its WAL cannot persist.
        Returns whether a fault was armed."""
        self._seq += 1
        roll = self.rng.random()
        if roll >= self.rate or self.exhausted:
            return False
        self.injected.append(
            Fault(kind="journal-disk", op="journal.append", seq=self._seq)
        )
        journal.fail_appends += 1
        return True

    def schedule_backend_fault(self, backend, ops: tuple[str, ...]) -> str | None:
        """Optionally arm ONE fault on a fake device backend
        (tpudev/fake.py ``fail_next``), drawn from the same seeded stream —
        device-layer chaos composes with apiserver chaos under one seed.
        Returns the op armed, or None."""
        self._seq += 1
        roll = self.rng.random()
        op = ops[self.rng.randrange(len(ops))]
        if roll >= self.rate or self.exhausted:
            return None
        self.injected.append(Fault(kind="backend", op=op, seq=self._seq))
        backend.fail_next(op)
        return op

    def schedule_preemption(self, backend) -> bool:
        """Optionally arm a platform preemption notice on a fake device
        backend (tpudev/fake.py ``set_preempted``), drawn from the seeded
        main stream like every other decision — same seed, same VMs get
        reclaimed at the same points. The armed notice carries the plan's
        ``preemption_deadline_s`` semantics: the scenario's agent has that
        long to fast-drain, checkpoint and publish its handoff before the
        modeled kill. Always advances the rng (an armed schedule must not
        reshuffle other modes' decisions). Returns whether armed."""
        self._seq += 1
        roll = self.rng.random()
        if roll >= self.preemption_rate or self.exhausted:
            return False
        self.injected.append(
            Fault(kind="preemption", op="preemption-notice", seq=self._seq)
        )
        backend.set_preempted(True)
        return True

    def seed_preemption(self, backend) -> None:
        """Arm one preemption notice unconditionally (acceptance tests and
        drills that need the scenario, not the odds). Recorded in the
        injected schedule like a drawn one; does not consume rng state."""
        self._seq += 1
        self.injected.append(
            Fault(kind="preemption", op="preemption-notice", seq=self._seq)
        )
        backend.set_preempted(True)

    def seed_prestage_kill(self, points: tuple[str, ...] = (
        "prestage-reserved", "prestage-armed", "prestage-invalidate",
    )) -> str:
        """Arm ONE orchestrator kill at a continuous-prestage crash
        point, the point drawn from the seeded main stream (the
        chaos_soak dual-wave leg needs the scenario — a SIGKILL landing
        mid-prestage of wave N+1 while wave N drains — not the odds;
        WHICH prestage point stays a pure function of the seed so a
        soak failure replays exactly). The armed point fires through
        :meth:`decide_orchestrator_kill`'s normal path via a one-shot
        force, recorded in the injected schedule like a drawn kill.
        Returns the point armed."""
        self._seq += 1
        point = points[self.rng.randrange(len(points))]
        self._forced_kill_point = point
        return point

    def seed_blackout_window(self) -> int:
        """Open ONE total-outage window unconditionally, its length in
        API calls drawn from the derived blackout stream (acceptance
        drills — SCALE_r04's parent-plane blackout — need the scenario,
        not the odds; the LENGTH stays a pure function of the seed so
        the drill replays exactly). Recorded in the injected schedule
        like a drawn window. Returns the window length armed."""
        span = self._blackout_rng.randint(
            self.blackout_min_calls, max(self.blackout_min_calls,
                                         self.blackout_max_calls)
        )
        self._seq += 1
        self.begin_blackout(calls=span)
        self.injected.append(
            Fault(kind=BLACKOUT_KIND, op="seeded-window", seq=self._seq)
        )
        return span

    # ---- brownout (gray-failure) mode -----------------------------------

    @property
    def brownout_active(self) -> bool:
        return self._brownout is not None

    @property
    def brownout_node(self) -> int | None:
        """Index of the node currently browning out, or None."""
        return self._brownout

    def seed_brownout(self, nodes: int = 1) -> int:
        """Arm a brownout on ONE node unconditionally, the victim's
        index drawn uniformly from ``nodes`` via the seeded main stream
        (the GRAY_r01 drill needs the scenario — a gray node the
        watchdog can't see — not the odds; WHICH node stays a pure
        function of the seed so a soak failure replays exactly).
        Recorded in the injected schedule like a drawn fault but NOT
        counted against ``max_faults`` — a brownout is weather the
        detector must see through, not budget the soak spends. The
        caller applies the factors to that node's executor/backend
        (serve/server.py ``set_brownout``, tpudev/fake.py
        ``set_brownout``) and routes its kube client's per-call
        slowness through :meth:`decide_brownout_slow`. Returns the
        victim index."""
        self._seq += 1
        idx = self.rng.randrange(max(1, nodes))
        self._brownout = idx
        self.injected.append(
            Fault(kind="brownout", op=f"node-{idx}", seq=self._seq)
        )
        return idx

    def clear_brownout(self) -> None:
        """Model the gray hardware recovering (the probation-lift leg):
        per-call slowness stops; the caller clears the executor/backend
        factors it applied at seed time."""
        self._brownout = None

    def decide_brownout_slow(self, op: str) -> float:
        """One intermittent-slowness decision for a kube call on the
        browning-out node: returns seconds to sleep (0.0 = this call is
        fast). The call still SUCCEEDS either way — brownout never
        errors, that is the point. Draws from the derived brownout
        stream on every call while armed so the schedule stays a pure
        function of (seed, call sequence) and never perturbs the main
        stream; decisions are not appended to ``injected`` (weather,
        not budget)."""
        if self._brownout is None:
            return 0.0
        roll = self._brownout_rng.random()
        jitter = self._brownout_rng.random()
        if roll >= self.brownout_kube_slow_rate:
            return 0.0
        self.brownout_slow_ops += 1
        return self.brownout_kube_slow_s * (0.5 + jitter)

    def seed_terminal_backend_fault(self, backend, ops: tuple[str, ...]) -> str:
        """Arm one TERMINAL device fault (``times=-1``: never clears) on an
        op drawn from the seeded stream — the chaos mode that drives the
        remediation ladder end-to-end: retries cannot fix it, device
        re-reset and runtime restart keep failing, and the node must end
        quarantined. Which op is condemned is a pure function of the seed,
        like every other decision; the caller clears the fault
        (``backend.fail.pop(op)``) to model hardware recovery for the
        probation-lift leg. Always injects (a terminal-fault soak without
        a terminal fault proves nothing). Returns the op armed."""
        self._seq += 1
        op = ops[self.rng.randrange(len(ops))]
        self.injected.append(Fault(kind="backend-terminal", op=op, seq=self._seq))
        backend.fail_next(op, times=-1)
        return op
