"""FaultyKubeClient: chaos in front of any KubeApi.

Wraps a real or fake client and consults a seeded
:class:`~tpu_cc_manager.faults.plan.FaultPlan` before each call:

- unary verbs may be throttled (429 + Retry-After), 5xx'd, connection-
  reset, or slowed — all injected BEFORE the inner call runs, modeling a
  request that never reached (or never returned from) the apiserver;
- watch connects may 410 immediately (stale rv → resync path) or hang up
  after a bounded number of events (transport death mid-stream).

Being a plain KubeApi, it composes anywhere: under the manager's watch
loop, under the rolling orchestrator, under pool attestation — and the
retry totals in utils/metrics.py show exactly what the faults cost.
"""

from __future__ import annotations

import logging
import time
from typing import Iterator, Mapping

from tpu_cc_manager.faults.plan import Fault, FaultPlan
from tpu_cc_manager.kubeclient.api import KubeApi, KubeApiError, WatchEvent

log = logging.getLogger(__name__)


class FaultyKubeClient(KubeApi):
    def __init__(
        self,
        inner: KubeApi,
        plan: FaultPlan,
        sleep=time.sleep,
        # How many events a hung-up watch yields before dying (decided per
        # hangup from the plan's rng via randrange, so it stays seeded).
        watch_hangup_after: int = 2,
    ) -> None:
        self.inner = inner
        self.plan = plan
        self.sleep = sleep
        self.watch_hangup_after = watch_hangup_after
        # Transparent to retry layering: wrapping RestKube must not
        # re-enable the caller-side ladder caller_retry_attempts collapses
        # (nested 3x3 amplification), and wrapping a fake must not disable
        # it.
        self.retries_internally = getattr(inner, "retries_internally", False)

    # ---- fault application ----------------------------------------------

    def _maybe_fault(self, op: str) -> None:
        # Brownout weather first (gray failure: the call SUCCEEDS, just
        # late — intermittently, from the plan's derived brownout
        # stream). Checked before the main-stream decision so a slow
        # call can still also draw a fault; neither perturbs the other's
        # schedule.
        brown = self.plan.decide_brownout_slow(op)
        if brown > 0:
            log.info("chaos: brownout slows %s by %.3fs", op, brown)
            self.sleep(brown)
        fault = self.plan.decide(op)
        if fault is None:
            return
        log.info("chaos: injecting %s", fault.describe())
        self._raise_or_delay(fault)

    def _raise_or_delay(self, fault: Fault) -> None:
        if fault.kind == "slow":
            self.sleep(fault.slow_s or 0.0)
            return
        if fault.kind == "http-429":
            raise KubeApiError(
                429, f"chaos: {fault.describe()}",
                retry_after_s=fault.retry_after_s,
            )
        if fault.kind in ("http-5xx", "stale-rv"):
            raise KubeApiError(fault.status, f"chaos: {fault.describe()}")
        # conn-reset / watch-hangup: transport-level failure.
        raise KubeApiError(None, f"chaos: {fault.describe()}")

    # ---- KubeApi ---------------------------------------------------------

    def get_node(self, name: str) -> dict:
        self._maybe_fault("get_node")
        return self.inner.get_node(name)

    def patch_node_labels(self, name: str, labels: Mapping[str, str | None]) -> dict:
        self._maybe_fault("patch_node_labels")
        return self.inner.patch_node_labels(name, labels)

    def patch_node_annotations(
        self, name: str, annotations: Mapping[str, str | None]
    ) -> dict:
        self._maybe_fault("patch_node_annotations")
        return self.inner.patch_node_annotations(name, annotations)

    def patch_node_taints(
        self, name: str, add: list[dict], remove_keys: list[str]
    ) -> dict:
        self._maybe_fault("patch_node_taints")
        return self.inner.patch_node_taints(name, add, remove_keys)

    def delete_node(self, name: str) -> None:
        """Harness passthrough (FakeKube.delete_node): chaos scenarios
        modeling a cluster-autoscaler scale-down delete through the same
        faulted surface the rest of the scenario rides, so a deletion can
        itself be throttled/5xx'd like a real autoscaler's would be."""
        self._maybe_fault("delete_node")
        return self.inner.delete_node(name)

    def list_nodes(self, label_selector: str | None = None) -> list[dict]:
        self._maybe_fault("list_nodes")
        return self.inner.list_nodes(label_selector)

    def list_nodes_page(
        self,
        label_selector: str | None = None,
        limit: int | None = None,
        continue_token: str | None = None,
    ) -> dict:
        # Faulted under the same op as the unchunked listing: a chaos
        # schedule that throttles lists throttles every page of them.
        self._maybe_fault("list_nodes")
        return self.inner.list_nodes_page(label_selector, limit, continue_token)

    def list_pods(
        self,
        namespace: str,
        label_selector: str | None = None,
        field_selector: str | None = None,
    ) -> list[dict]:
        self._maybe_fault("list_pods")
        return self.inner.list_pods(namespace, label_selector, field_selector)

    def create_event(self, namespace: str, event: dict) -> dict:
        # Events are best-effort by contract; still fault them — a caller
        # that lets an event failure break a reconcile is a bug the soak
        # should catch.
        self._maybe_fault("create_event")
        return self.inner.create_event(namespace, event)

    def self_subject_access_review(
        self, verb: str, resource: str, namespace: str | None = None
    ) -> bool:
        self._maybe_fault("ssar")
        return self.inner.self_subject_access_review(verb, resource, namespace)

    # Lease verbs: faulted like any unary call, so the rollout lease's
    # acquire/renew/checkpoint paths prove themselves under throttling and
    # connection resets — a renew loop that dies on one 429 would silently
    # forfeit the lease mid-rollout.

    def get_lease(self, namespace: str, name: str) -> dict:
        self._maybe_fault("get_lease")
        return self.inner.get_lease(namespace, name)

    def create_lease(self, namespace: str, name: str, spec: dict) -> dict:
        self._maybe_fault("create_lease")
        return self.inner.create_lease(namespace, name, spec)

    def update_lease(self, namespace: str, name: str, lease: dict) -> dict:
        self._maybe_fault("update_lease")
        return self.inner.update_lease(namespace, name, lease)

    def delete_lease(self, namespace: str, name: str) -> None:
        self._maybe_fault("delete_lease")
        return self.inner.delete_lease(namespace, name)

    def watch_nodes(
        self,
        name: str,
        resource_version: str | None = None,
        timeout_seconds: int = 300,
    ) -> Iterator[WatchEvent]:
        return self._faulted_watch(
            self.inner.watch_nodes(name, resource_version, timeout_seconds)
        )

    def watch_nodes_pool(
        self,
        label_selector: str | None = None,
        resource_version: str | None = None,
        timeout_seconds: int = 300,
    ) -> Iterator[WatchEvent]:
        # Same fault vocabulary as the single-node watch: the informer
        # cache's transport must prove itself against hangups, stale-rv
        # 410s and blackouts just like the agent's watch loop does.
        return self._faulted_watch(
            self.inner.watch_nodes_pool(
                label_selector, resource_version, timeout_seconds
            )
        )

    def _faulted_watch(self, stream: Iterator[WatchEvent]) -> Iterator[WatchEvent]:
        fault = self.plan.decide_watch()
        if fault is not None and fault.kind == "stale-rv":
            log.info("chaos: injecting %s", fault.describe())
            raise KubeApiError(410, f"chaos: {fault.describe()}")
        if fault is not None and fault.kind == "blackout":
            # Total outage: the watch connect is refused like every other
            # verb — no events leak through a dead apiserver.
            log.info("chaos: injecting %s", fault.describe())
            raise KubeApiError(None, f"chaos: {fault.describe()}")
        if fault is None:
            yield from stream
            return
        # watch-hangup: pass through a bounded number of events, then die
        # with a transport error (the stream the server closed mid-read).
        log.info("chaos: injecting %s", fault.describe())
        yielded = 0
        for event in stream:
            yield event
            yielded += 1
            if yielded >= self.watch_hangup_after:
                break
        raise KubeApiError(None, f"chaos: {fault.describe()} after {yielded} event(s)")
