"""Deterministic chaos fault injection for the control plane.

The north star demands a control plane that "handles as many scenarios as
you can imagine" — which only counts if the scenarios are *injectable* and
recovery is *provable*. This package supplies the apiserver half of that
(the device half already exists: ``tpudev/fake.py``'s ``fail_next`` hooks,
which :class:`~tpu_cc_manager.faults.plan.FaultPlan` can drive from the
same seed):

- :class:`~tpu_cc_manager.faults.plan.FaultPlan` — a seeded, reproducible
  schedule of faults (``CC_CHAOS_SEED``): same seed + same call sequence
  → byte-identical fault schedule, so a chaos failure is replayable;
- :class:`~tpu_cc_manager.faults.kube.FaultyKubeClient` — a KubeApi
  wrapper injecting 429+Retry-After, 5xx, connection resets, slow
  responses, watch hangups, and stale-rv 410s in front of any real or
  fake client.

Consumed by tests/test_chaos.py (fast deterministic subset, ``chaos``
pytest marker) and hack/chaos_soak.sh (the longer seeded soak).
"""

from tpu_cc_manager.faults.kube import FaultyKubeClient
from tpu_cc_manager.faults.plan import (
    CHAOS_SEED_ENV,
    Fault,
    FaultPlan,
    OrchestratorKilled,
)

__all__ = [
    "CHAOS_SEED_ENV",
    "Fault",
    "FaultPlan",
    "FaultyKubeClient",
    "OrchestratorKilled",
]
