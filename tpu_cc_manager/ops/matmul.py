"""Tiled bf16 matmul with f32 VMEM accumulation, as a pallas kernel.

The MXU-canonical pattern: 3-D grid over (M, N, K) tiles, K innermost so
each (i, j) output tile accumulates across the K walk in a f32 VMEM
scratch, written back once on the last K step. Used by the matmul smoke
workload's ``kernel='pallas'`` mode to prove custom-kernel compilation on a
freshly reconfigured slice (the XLA path proves the stock compiler; this
proves Mosaic).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Compat shim for the pallas compiler-params rename: newer JAX exposes
# ``pltpu.CompilerParams``, older releases (<= 0.4.x) only the deprecated
# ``pltpu.TPUCompilerParams``. Same constructor signature for the fields
# used here (dimension_semantics).
_CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams"
)


# Measured per-generation default tilings for ``tiled_matmul``. Retuned
# from `hack/tune_pallas.sh` sweep artifacts, not guesswork: v5e's entry
# is the r4-measured 512^3 (76.0 % MFU, artifacts/smoke_pallas_tpu_r04
# .json) pending the r5 full-K sweep; unknown generations inherit it.
DEFAULT_BLOCKS: dict[str, tuple[int, int, int]] = {
    "v5e": (512, 512, 512),
}
_FALLBACK_BLOCKS = (512, 512, 512)


def default_blocks(generation: str | None, size: int) -> tuple[int, int, int]:
    """Best-known (block_m, block_n, block_k) for a square bf16 matmul of
    ``size`` on ``generation`` (None → CPU/interpret). Entries are clamped
    to divide ``size``: a non-dividing dimension halves until it does, so
    callers always get a legal tiling for any size that is a multiple of a
    small power of two."""
    blocks = DEFAULT_BLOCKS.get(generation or "", _FALLBACK_BLOCKS)
    out = []
    for b in blocks:
        b = max(1, min(b, size))
        while size % b:
            b //= 2
        out.append(b)
    return tuple(out)


def _mm_kernel(a_ref, b_ref, o_ref, acc_ref, *, k_steps: int):
    @pl.when(pl.program_id(2) == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += jnp.dot(
        a_ref[:], b_ref[:], preferred_element_type=jnp.float32
    )

    @pl.when(pl.program_id(2) == k_steps - 1)
    def _():
        o_ref[:] = acc_ref[:].astype(o_ref.dtype)


def tiled_matmul(
    a: jnp.ndarray,
    b: jnp.ndarray,
    block_m: int = 512,
    block_n: int = 512,
    block_k: int = 512,
    out_dtype=jnp.float32,
) -> jnp.ndarray:
    """a: (M, K) @ b: (K, N) -> (M, N). Dims must divide by the blocks
    (callers pad; the smoke workload always passes multiples of 128)."""
    M, K = a.shape
    K2, N = b.shape
    assert K == K2, (a.shape, b.shape)
    block_m = min(block_m, M)
    block_n = min(block_n, N)
    block_k = min(block_k, K)
    if M % block_m or N % block_n or K % block_k:
        raise ValueError(
            f"shapes ({M},{K})x({K},{N}) not divisible by blocks "
            f"({block_m},{block_n},{block_k})"
        )
    k_steps = K // block_k
    grid = (M // block_m, N // block_n, k_steps)

    return pl.pallas_call(
        functools.partial(_mm_kernel, k_steps=k_steps),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_m, block_k), lambda i, j, k: (i, k)),
            pl.BlockSpec((block_k, block_n), lambda i, j, k: (k, j)),
        ],
        out_specs=pl.BlockSpec((block_m, block_n), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((M, N), out_dtype),
        scratch_shapes=[pltpu.VMEM((block_m, block_n), jnp.float32)],
        # M/N tiles are independent (parallel); the K walk carries the
        # accumulator (arbitrary). Declaring this lets Mosaic pipeline the
        # K steps and reorder/parallelize output tiles.
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=2 * M * N * K,
            bytes_accessed=(M * K + K * N) * a.dtype.itemsize + M * N * 4,
            transcendentals=0,
        ),
        interpret=jax.default_backend() != "tpu",
    )(a, b)
