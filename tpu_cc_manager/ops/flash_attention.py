"""Flash attention (fused online-softmax) as pallas TPU kernels.

Forward pass never materializes the (S, S) score matrix: the grid walks
query blocks, and an inner fori_loop streams key/value blocks through VMEM
maintaining the running max / normalizer / accumulator (the Dao et al.
online-softmax recurrence), saving per-row logsumexp for the backward.

Backward is flash too (standard block recomputation): two pallas kernels —
dQ over query blocks, dK/dV over key blocks — rebuild each P block as
``exp(s − lse)`` from the saved inputs, so training memory stays
O(S·D + S), never O(S²). The classic identity
``dS = P ∘ (dP − rowsum(dO ∘ O))`` supplies the softmax backward without
storing P.

Layout: (B, H, S, D) with D the head dim (<=128: one MXU lane tile).
Causal and non-causal. On CPU the kernels run in pallas interpreter mode.

Reference counterpart: none (the reference has no ML/kernel code,
SURVEY.md §2); this exists for the smoke/validation workloads and the
long-context training path (models/llama.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def _block_for(requested: int, seq_len: int) -> int:
    """Clamp a block size to the sequence, with BOTH rounded up to a
    multiple of 8: Mosaic requires sublane-dim block sizes divisible by 8
    and dynamic-slice offsets (``ki * block``) statically provable as
    multiples of 8 — a caller-supplied odd block must be aligned too. A
    block may exceed the (padded/masked) array tail — an unaligned one may
    not exist at all."""
    rounded = (requested + 7) // 8 * 8
    return min(rounded, (seq_len + 7) // 8 * 8)


def reference_attention(q, k, v, causal: bool = True):
    """Plain-XLA attention, the numerics oracle for the kernels."""
    _, _, S, D = q.shape
    scores = jnp.einsum(
        "bhsd,bhtd->bhst", q, k, preferred_element_type=jnp.float32
    ) / (D**0.5)
    if causal:
        t = jnp.arange(S)
        mask = t[None, :] <= t[:, None]
        scores = jnp.where(mask[None, None], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhst,bhtd->bhsd", probs.astype(v.dtype), v)


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_q: int,
                block_k: int, seq_len: int, causal: bool):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)  # (block_q, D)
    scale = 1.0 / (q.shape[-1] ** 0.5)

    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, q.shape[-1]), jnp.float32)

    num_k_blocks = pl.cdiv(seq_len, block_k)
    if causal:
        # Skip key blocks strictly after this query block's last position
        # (valid for any block_q/block_k ratio).
        last_q_pos = (qi + 1) * block_q - 1
        k_hi = jnp.minimum(last_q_pos // block_k + 1, num_k_blocks)
    else:
        k_hi = num_k_blocks

    def body(ki, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # (block_q, block_k)
        # Tail padding: when seq_len % block_k != 0 the last key block reads
        # past the sequence; those phantom keys must never enter the softmax
        # (causal or not).
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        valid = k_pos < seq_len
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            valid = valid & (k_pos <= q_pos)
        s = jnp.where(valid, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = alpha * acc + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, k_hi, body, (m0, l0, acc0))
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l_safe).astype(o_ref.dtype)
    # Per-row logsumexp in the scaled-score domain; the backward rebuilds
    # each P block as exp(s - lse). Layout (BH, S, 1) — a column vector —
    # so every block shape is Mosaic-legal (sublane dim divisible by 8,
    # lane dim equal to the array's) and the backward's dynamic slices run
    # on the 8-granular sublane dim, never the 128-granular lane dim.
    lse_ref[0] = m + jnp.log(l_safe)


def _flash_forward(q, k, v, causal: bool, block_q: int, block_k: int,
                   interpret: bool):
    B, H, S, D = q.shape
    block_q = _block_for(block_q, S)
    block_k = _block_for(block_k, S)
    grid = (B * H, pl.cdiv(S, block_q))

    qr = q.reshape(B * H, S, D)
    kr = k.reshape(B * H, S, D)
    vr = v.reshape(B * H, S, D)

    # Pad keys/values to a block multiple: the kernel's pl.ds slice clamps
    # at the buffer end (dynamic-slice semantics), so an unpadded tail block
    # would silently re-read earlier rows under a wrong k_pos. The in-kernel
    # `k_pos < seq_len` mask nulls the zero-padded phantoms.
    s_pad = pl.cdiv(S, block_k) * block_k
    if s_pad != S:
        kr = jnp.pad(kr, ((0, 0), (0, s_pad - S), (0, 0)))
        vr = jnp.pad(vr, ((0, 0), (0, s_pad - S), (0, 0)))

    out, lse = pl.pallas_call(
        functools.partial(
            _fwd_kernel, block_q=block_q, block_k=block_k,
            seq_len=S, causal=causal,
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, s_pad, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, s_pad, D), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, S, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, S, 1), jnp.float32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=4 * B * H * S * S * D,
            bytes_accessed=(3 * B * H * S * D + B * H * S * D) * q.dtype.itemsize,
            transcendentals=B * H * S * S,
        ),
        interpret=interpret,
    )(qr, kr, vr)
    return out.reshape(B, H, S, D), lse


# ---------------------------------------------------------------------------
# Backward (flash: block recomputation from saved q/k/v/lse)
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *,
                   block_q: int, block_k: int, seq_len: int, causal: bool):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)          # (block_q, D)
    do = do_ref[0].astype(jnp.float32)        # (block_q, D)
    lse = lse_ref[0]                          # (block_q, 1)
    delta = delta_ref[0]                      # (block_q, 1)
    scale = 1.0 / (q.shape[-1] ** 0.5)

    num_k_blocks = pl.cdiv(seq_len, block_k)
    if causal:
        last_q_pos = (qi + 1) * block_q - 1
        k_hi = jnp.minimum(last_q_pos // block_k + 1, num_k_blocks)
    else:
        k_hi = num_k_blocks

    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )

    def body(ki, acc):
        k_blk = k_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        # q_pos < seq_len guards the partial tail query block: its phantom
        # rows are dropped on write, but NEG_INF − garbage-lse can overflow
        # exp; keep them exactly zero instead.
        valid = (k_pos < seq_len) & (q_pos < seq_len)
        if causal:
            valid = valid & (k_pos <= q_pos)
        s = jnp.where(valid, s, NEG_INF)
        p = jnp.exp(s - lse)                       # recomputed P block
        dp = jax.lax.dot_general(                  # dP = dO Vᵀ
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta) * scale              # softmax backward
        return acc + jax.lax.dot_general(          # dQ += dS K
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    acc0 = jnp.zeros((block_q, q.shape[-1]), jnp.float32)
    dq_ref[0] = jax.lax.fori_loop(0, k_hi, body, acc0).astype(dq_ref.dtype)


def _bwd_dkv_kernel(k_ref, v_ref, q_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *, block_q: int, block_k: int,
                    seq_len: int, padded_q_len: int, causal: bool):
    ki = pl.program_id(1)
    k_blk = k_ref[0].astype(jnp.float32)      # (block_k, D)
    v_blk = v_ref[0].astype(jnp.float32)
    D = k_blk.shape[-1]
    scale = 1.0 / (D**0.5)

    num_q_blocks = padded_q_len // block_q
    # Causal: query blocks strictly before this key block contribute nothing.
    start = (ki * block_k) // block_q if causal else 0
    k_pos = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )

    def body(qi, carry):
        dk, dv = carry
        q_blk = q_ref[0, pl.ds(qi * block_q, block_q), :].astype(jnp.float32)
        do_blk = do_ref[0, pl.ds(qi * block_q, block_q), :].astype(jnp.float32)
        lse_blk = lse_ref[0, pl.ds(qi * block_q, block_q), :]      # (bq, 1)
        delta_blk = delta_ref[0, pl.ds(qi * block_q, block_q), :]  # (bq, 1)
        s = jax.lax.dot_general(
            q_blk, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # (block_q, block_k)
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        # Phantom (zero-padded) query rows carry lse=0/delta=0; masking s to
        # NEG_INF makes their recomputed P rows exactly zero, so they add
        # nothing to dK/dV. Phantom key columns are sliced away by the
        # caller.
        valid = (q_pos < seq_len) & (k_pos < seq_len)
        if causal:
            valid = valid & (k_pos <= q_pos)
        s = jnp.where(valid, s, NEG_INF)
        p = jnp.exp(s - lse_blk)
        dv = dv + jax.lax.dot_general(             # dV += Pᵀ dO
            p, do_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(                  # dP = dO Vᵀ
            do_blk, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_blk) * scale
        dk = dk + jax.lax.dot_general(             # dK += dSᵀ Q
            ds, q_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return dk, dv

    dk0 = jnp.zeros((block_k, D), jnp.float32)
    dv0 = jnp.zeros((block_k, D), jnp.float32)
    dk, dv = jax.lax.fori_loop(start, num_q_blocks, body, (dk0, dv0))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_backward(q, k, v, out, lse, g, causal: bool, block_q: int,
                    block_k: int, interpret: bool):
    B, H, S, D = q.shape
    block_q = _block_for(block_q, S)
    block_k = _block_for(block_k, S)

    qr = q.reshape(B * H, S, D)
    kr = k.reshape(B * H, S, D)
    vr = v.reshape(B * H, S, D)
    dor = g.reshape(B * H, S, D)
    outr = out.reshape(B * H, S, D)

    # delta_i = rowsum(dO ∘ O): the softmax-backward correction term,
    # computed once in XLA (elementwise + reduce; no S² anywhere). Shaped
    # (B*H, S, 1) like lse (see the forward kernel's layout note).
    delta = jnp.sum(
        dor.astype(jnp.float32) * outr.astype(jnp.float32),
        axis=-1, keepdims=True,
    )  # (B*H, S, 1)

    s_pad_k = pl.cdiv(S, block_k) * block_k
    kr_p, vr_p = kr, vr
    if s_pad_k != S:
        kr_p = jnp.pad(kr, ((0, 0), (0, s_pad_k - S), (0, 0)))
        vr_p = jnp.pad(vr, ((0, 0), (0, s_pad_k - S), (0, 0)))
    # lse/delta zero-padded to the query-block grid: both kernels read them
    # in block_q-sized pieces, and a block that is neither 128-divisible
    # nor the whole (unpadded) dim is illegal on TPU. Zeros keep phantom
    # rows exactly zero after the s=NEG_INF mask (see kernel comments).
    s_pad_q = pl.cdiv(S, block_q) * block_q
    qr_p, dor_p, lse_p, delta_p = qr, dor, lse, delta
    if s_pad_q != S:
        pad = s_pad_q - S
        qr_p = jnp.pad(qr, ((0, 0), (0, pad), (0, 0)))
        dor_p = jnp.pad(dor, ((0, 0), (0, pad), (0, 0)))
        lse_p = jnp.pad(lse, ((0, 0), (0, pad), (0, 0)))
        delta_p = jnp.pad(delta, ((0, 0), (0, pad), (0, 0)))

    # --- dQ: grid over query blocks, stream key blocks -------------------
    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, block_q=block_q, block_k=block_k,
            seq_len=S, causal=causal,
        ),
        grid=(B * H, pl.cdiv(S, block_q)),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, s_pad_k, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, s_pad_k, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, S, D), q.dtype),
        cost_estimate=pl.CostEstimate(
            flops=5 * B * H * S * S * D,
            bytes_accessed=4 * B * H * S * D * q.dtype.itemsize,
            transcendentals=B * H * S * S,
        ),
        interpret=interpret,
    )(qr, kr_p, vr_p, dor_p, lse_p, delta_p)

    # --- dK/dV: grid over key blocks, stream query blocks ----------------
    # dk/dv outputs are block_k-grid padded; phantom key rows are zero
    # (masked) and sliced away below.
    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, block_q=block_q, block_k=block_k,
            seq_len=S, padded_q_len=s_pad_q, causal=causal,
        ),
        grid=(B * H, s_pad_k // block_k),
        in_specs=[
            pl.BlockSpec((1, block_k, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, s_pad_q, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, s_pad_q, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, s_pad_q, 1), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, s_pad_q, 1), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, s_pad_k, D), k.dtype),
            jax.ShapeDtypeStruct((B * H, s_pad_k, D), v.dtype),
        ],
        cost_estimate=pl.CostEstimate(
            flops=5 * B * H * S * S * D,
            bytes_accessed=4 * B * H * S * D * q.dtype.itemsize,
            transcendentals=B * H * S * S,
        ),
        interpret=interpret,
    )(kr_p, vr_p, qr_p, dor_p, lse_p, delta_p)
    if s_pad_k != S:
        dk = dk[:, :S]
        dv = dv[:, :S]

    return (
        dq.reshape(B, H, S, D),
        dk.reshape(B, H, S, D),
        dv.reshape(B, H, S, D),
    )


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(q, k, v, causal: bool = True, block_q: int = 128,
                    block_k: int = 128):
    """Fused causal attention. q/k/v: (B, H, S, D); returns (B, H, S, D)."""
    interpret = jax.default_backend() != "tpu"
    out, _ = _flash_forward(q, k, v, causal, block_q, block_k, interpret)
    return out


def _fwd_rule(q, k, v, causal, block_q, block_k):
    interpret = jax.default_backend() != "tpu"
    out, lse = _flash_forward(q, k, v, causal, block_q, block_k, interpret)
    return out, (q, k, v, out, lse)


def _bwd_rule(causal, block_q, block_k, residuals, g):
    q, k, v, out, lse = residuals
    interpret = jax.default_backend() != "tpu"
    return _flash_backward(
        q, k, v, out, lse, g, causal, block_q, block_k, interpret
    )


flash_attention.defvjp(_fwd_rule, _bwd_rule)
