"""Flash attention (fused online-softmax) as a pallas TPU kernel.

Forward pass never materializes the (S, S) score matrix: the grid walks
query blocks, and an inner fori_loop streams key/value blocks through VMEM
maintaining the running max / normalizer / accumulator (the
Dao et al. online-softmax recurrence). Backward recomputes attention from
the saved inputs with the plain-XLA reference implementation — flash's
standard memory/FLOPs trade, and exact to f32 accumulation either way.

Layout: (B, H, S, D) with D the head dim (<=128: one MXU lane tile).
Causal only (that is what the smoke models need). On CPU the kernel runs in
pallas interpreter mode.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def reference_attention(q, k, v, causal: bool = True):
    """Plain-XLA attention, the numerics oracle and the backward path."""
    _, _, S, D = q.shape
    scores = jnp.einsum(
        "bhsd,bhtd->bhst", q, k, preferred_element_type=jnp.float32
    ) / (D**0.5)
    if causal:
        t = jnp.arange(S)
        mask = t[None, :] <= t[:, None]
        scores = jnp.where(mask[None, None], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhst,bhtd->bhsd", probs.astype(v.dtype), v)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, block_q: int, block_k: int,
                seq_len: int, causal: bool):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)  # (block_q, D)
    scale = 1.0 / (q.shape[-1] ** 0.5)

    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, q.shape[-1]), jnp.float32)

    num_k_blocks = pl.cdiv(seq_len, block_k)
    if causal:
        # Skip key blocks strictly after this query block's last position
        # (valid for any block_q/block_k ratio).
        last_q_pos = (qi + 1) * block_q - 1
        k_hi = jnp.minimum(last_q_pos // block_k + 1, num_k_blocks)
    else:
        k_hi = num_k_blocks

    def body(ki, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # (block_q, block_k)
        # Tail padding: when seq_len % block_k != 0 the last key block reads
        # past the sequence; those phantom keys must never enter the softmax
        # (causal or not).
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        valid = k_pos < seq_len
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            valid = valid & (k_pos <= q_pos)
        s = jnp.where(valid, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = alpha * acc + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, k_hi, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _flash_forward(q, k, v, causal: bool, block_q: int, block_k: int,
                   interpret: bool):
    B, H, S, D = q.shape
    block_q = min(block_q, S)
    block_k = min(block_k, S)
    grid = (B * H, pl.cdiv(S, block_q))

    qr = q.reshape(B * H, S, D)
    kr = k.reshape(B * H, S, D)
    vr = v.reshape(B * H, S, D)

    # Pad keys/values to a block multiple: the kernel's pl.ds slice clamps
    # at the buffer end (dynamic-slice semantics), so an unpadded tail block
    # would silently re-read earlier rows under a wrong k_pos. The in-kernel
    # `k_pos < seq_len` mask nulls the zero-padded phantoms.
    s_pad = pl.cdiv(S, block_k) * block_k
    if s_pad != S:
        kr = jnp.pad(kr, ((0, 0), (0, s_pad - S), (0, 0)))
        vr = jnp.pad(vr, ((0, 0), (0, s_pad - S), (0, 0)))

    out = pl.pallas_call(
        functools.partial(
            _fwd_kernel, block_q=block_q, block_k=block_k,
            seq_len=S, causal=causal,
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, s_pad, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, s_pad, D), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, S, D), q.dtype),
        cost_estimate=pl.CostEstimate(
            flops=4 * B * H * S * S * D,
            bytes_accessed=(3 * B * H * S * D + B * H * S * D) * q.dtype.itemsize,
            transcendentals=B * H * S * S,
        ),
        interpret=interpret,
    )(qr, kr, vr)
    return out.reshape(B, H, S, D)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(q, k, v, causal: bool = True, block_q: int = 128,
                    block_k: int = 128):
    """Fused causal attention. q/k/v: (B, H, S, D); returns (B, H, S, D)."""
    interpret = jax.default_backend() != "tpu"
    return _flash_forward(q, k, v, causal, block_q, block_k, interpret)


def _fwd_rule(q, k, v, causal, block_q, block_k):
    out = flash_attention(q, k, v, causal, block_q, block_k)
    return out, (q, k, v)


def _bwd_rule(causal, block_q, block_k, residuals, g):
    q, k, v = residuals
    _, vjp = jax.vjp(lambda q, k, v: reference_attention(q, k, v, causal), q, k, v)
    return vjp(g)


flash_attention.defvjp(_fwd_rule, _bwd_rule)
