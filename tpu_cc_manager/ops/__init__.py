"""Pallas TPU kernels and sequence-parallel primitives for the smoke models.

No reference counterpart (the reference has no compute path at all,
SURVEY.md §2); these exist so the validation workloads exercise the same
hot ops a production TPU serving/training stack would:

- :mod:`flash_attention` — fused online-softmax attention (pallas, MXU),
- :mod:`matmul` — tiled f32-accumulating bf16 matmul (pallas),
- :mod:`ring_attention` — ring/sequence parallelism over an ICI mesh axis
  via shard_map + ppermute (the long-context path).

Kernels compile on TPU; on CPU (tests, dry-runs) they run in pallas
interpreter mode, selected automatically.
"""

from tpu_cc_manager.ops.flash_attention import flash_attention
from tpu_cc_manager.ops.matmul import tiled_matmul
from tpu_cc_manager.ops.ring_attention import ring_attention

__all__ = ["flash_attention", "tiled_matmul", "ring_attention"]
