"""Ring attention: sequence/context parallelism over an ICI mesh axis.

The long-context path (a first-class requirement): the sequence dimension is
sharded across devices on one mesh axis; each device holds its Q shard
permanently and streams every K/V shard past it around the ring with
``lax.ppermute`` (one hop per step, bandwidth rides the ICI torus), merging
partial attention results with the same online-softmax recurrence flash
attention uses block-locally. Peak memory per device is O(S/n · S/n) scores
— full-sequence attention without any device ever holding full K/V.

Expressed with ``shard_map`` + XLA collectives (not raw RDMA) so the same
code runs on the CPU test mesh and compiles to ICI collective-permutes on
TPU.

Causal handling: ring step r on device i processes the K/V shard that
started at device (i - r) mod n. With sequence shards laid out in device
order, that shard covers keys strictly before this device's queries when
(i - r) mod n < i — full block; equal — local causal block; later — the
attention math is skipped with ``lax.cond`` (every score would be masked);
the ppermute itself still runs on every step so all devices join each
collective.
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

NEG_INF = -1e30


def ring_spec(mesh: Mesh, axis: str, B: int, H: int, KV: int) -> P:
    """The PartitionSpec ring attention uses for (B, heads, S, D) tensors:
    sequence on ``axis``, batch on every other non-tp axis, heads on 'tp'.

    Shapes are static at trace time: batch/head sharding is dropped when a
    dimension doesn't divide (e.g. the batch-1 init trace) — the math is
    identical, just replicated over those axes for that trace.
    """
    import math

    batch_axes = tuple(a for a in mesh.axis_names if a not in (axis, "tp"))
    if batch_axes and B % math.prod(mesh.shape[a] for a in batch_axes):
        batch_axes = ()
    head_axis = "tp" if ("tp" in mesh.axis_names and axis != "tp") else None
    if head_axis and (KV % mesh.shape["tp"] or H % mesh.shape["tp"]):
        head_axis = None
    return P(batch_axes or None, head_axis, axis, None)


def _block_attn(q, k, v, q_off, k_off, scale):
    """Partial (unnormalized-softmax) attention of a Q shard against one K/V
    shard with absolute-position causal masking. Returns (m, l, acc).

    Grouped-query layout: q is (B, KV, G, S, D), k/v are (B, KV, T, D) —
    K/V stay KV-head-shaped (never repeated to full head count), so ring
    traffic and per-device K/V memory are 1/G of the repeated form."""
    s = jnp.einsum("bkgsd,bktd->bkgst", q, k, preferred_element_type=jnp.float32)
    s = s * scale
    Sq, Sk = q.shape[3], k.shape[2]
    q_pos = q_off + jnp.arange(Sq)
    k_pos = k_off + jnp.arange(Sk)
    mask = k_pos[None, :] <= q_pos[:, None]
    s = jnp.where(mask[None, None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    # Guard fully-masked rows (m == NEG_INF) against exp overflow to nan.
    m_safe = jnp.maximum(m, -1e29)
    p = jnp.exp(s - m_safe)
    p = jnp.where(mask[None, None, None], p, 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)
    acc = jnp.einsum("bkgst,bktd->bkgsd", p, v.astype(jnp.float32))
    return m_safe, l, acc


def _merge(m1, l1, acc1, m2, l2, acc2):
    """Merge two online-softmax partials."""
    m = jnp.maximum(m1, m2)
    a1 = jnp.exp(m1 - m)
    a2 = jnp.exp(m2 - m)
    return m, a1 * l1 + a2 * l2, a1 * acc1 + a2 * acc2


def ring_attention_in_jit(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    axis: str = "dp",
) -> jnp.ndarray:
    """Jit-composable ring attention: no device_put, caller owns placement.

    Safe to call from inside a jitted model forward (shard_map composes
    with the surrounding pjit; the in_specs act as sharding constraints).
    q: (B, H, S, D); k/v: (B, KV, S, D) with H divisible by KV (GQA — K/V
    are streamed KV-head-shaped, never repeated). S divisible by the axis
    size. Batch rides every mesh axis except ``axis`` and 'tp'; heads ride
    'tp' when present — so wiring the ring into a dp/fsdp/tp-sharded train
    step adds no cross-axis regather of activations.
    """
    n = mesh.shape[axis]
    B, H, S, D = q.shape
    KV = k.shape[1]
    if S % n:
        raise ValueError(f"sequence {S} not divisible by ring size {n}")
    if H % KV:
        raise ValueError(f"{H} query heads not divisible by {KV} kv heads")
    shard = S // n
    scale = 1.0 / (D**0.5)
    spec = ring_spec(mesh, axis, B, H, KV)

    def local(q, k, v):
        idx = jax.lax.axis_index(axis)
        q_off = idx * shard
        # Local grouped layout: (B, KV, G, S, D); KV here is the local
        # (possibly tp-sharded) kv-head count.
        kv_local = k.shape[1]
        q = q.reshape(q.shape[0], kv_local, q.shape[1] // kv_local,
                      q.shape[2], q.shape[3])

        m, l, acc = _block_attn(q, k, v, q_off, idx * shard, scale)

        def body(r, carry):
            k_cur, v_cur, m, l, acc = carry
            # Pass K/V to the next device; receive from the previous one.
            # The ppermute runs unconditionally (every device must join the
            # collective); only the attention math is skipped.
            perm = [(i, (i + 1) % n) for i in range(n)]
            k_cur = jax.lax.ppermute(k_cur, axis, perm)
            v_cur = jax.lax.ppermute(v_cur, axis, perm)
            src = (idx - r) % n  # owner of the shard we just received

            def attend(operand):
                k_in, v_in, m, l, acc = operand
                m2, l2, acc2 = _block_attn(
                    q, k_in, v_in, q_off, src * shard, scale
                )
                return _merge(m, l, acc, m2, l2, acc2)

            # Shards owned by later devices are entirely in this Q shard's
            # future: every score would be masked, so skip the two einsums
            # (on average (n-1)/2 steps per device — half the ring FLOPs).
            m, l, acc = jax.lax.cond(
                src <= idx,
                attend,
                lambda operand: (operand[2], operand[3], operand[4]),
                (k_cur, v_cur, m, l, acc),
            )
            return k_cur, v_cur, m, l, acc

        _, _, m, l, acc = jax.lax.fori_loop(1, n, body, (k, v, m, l, acc))
        out = (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)
        # (B, KV, G, S, D) -> (B, H, S, D)
        return out.reshape(out.shape[0], -1, out.shape[3], out.shape[4])

    mapped = shard_map(
        local,
        mesh=mesh,
        in_specs=(spec,) * 3,
        out_specs=spec,
        # The skip-future-shards lax.cond takes different collective paths
        # per branch; at sp>2 JAX's static replication checker cannot
        # prove the branches' replication types equal and aborts tracing.
        # The branches are element-wise equivalent in rep terms (both
        # return (m, l, acc) sharded exactly like the carry), so disable
        # the check rather than the FLOP-saving skip.
        check_rep=False,
    )
    return mapped(q, k, v)


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    axis: str = "dp",
) -> jnp.ndarray:
    """Standalone entry: places Q/K/V with the same spec the kernel uses
    (sequence on ``axis``, batch/heads on their mesh shards — see
    :func:`ring_spec`), then runs :func:`ring_attention_in_jit`.
    q: (B, H, S, D), k/v: (B, KV, S, D); returns (B, H, S, D) with that
    spec."""
    spec = ring_spec(mesh, axis, q.shape[0], q.shape[1], k.shape[1])
    sharding = NamedSharding(mesh, spec)
    q = jax.device_put(q, sharding)
    k = jax.device_put(k, sharding)
    v = jax.device_put(v, sharding)
    return ring_attention_in_jit(q, k, v, mesh, axis)
