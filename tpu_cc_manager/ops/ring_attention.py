"""Ring attention: sequence/context parallelism over an ICI mesh axis.

The long-context path (a first-class requirement): the sequence dimension is
sharded across devices on one mesh axis; each device holds its Q shard
permanently and streams every K/V shard past it around the ring with
``lax.ppermute`` (one hop per step, bandwidth rides the ICI torus), merging
partial attention results with the same online-softmax recurrence flash
attention uses block-locally. Peak memory per device is O(S/n · S/n) scores
— full-sequence attention without any device ever holding full K/V.

Expressed with ``shard_map`` + XLA collectives (not raw RDMA) so the same
code runs on the CPU test mesh and compiles to ICI collective-permutes on
TPU.

Causal handling: ring step r on device i processes the K/V shard that
started at device (i - r) mod n. With sequence shards laid out in device
order, that shard covers keys strictly before this device's queries when
(i - r) mod n < i — full block; equal — local causal block; later — the
attention math is skipped with ``lax.cond`` (every score would be masked);
the ppermute itself still runs on every step so all devices join each
collective.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

NEG_INF = -1e30


def _block_attn(q, k, v, q_off, k_off, scale):
    """Partial (unnormalized-softmax) attention of a Q shard against one K/V
    shard with absolute-position causal masking. Returns (m, l, acc)."""
    s = jnp.einsum("bhsd,bhtd->bhst", q, k, preferred_element_type=jnp.float32)
    s = s * scale
    Sq, Sk = q.shape[2], k.shape[2]
    q_pos = q_off + jnp.arange(Sq)
    k_pos = k_off + jnp.arange(Sk)
    mask = k_pos[None, :] <= q_pos[:, None]
    s = jnp.where(mask[None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    # Guard fully-masked rows (m == NEG_INF) against exp overflow to nan.
    m_safe = jnp.maximum(m, -1e29)
    p = jnp.exp(s - m_safe)
    p = jnp.where(mask[None, None], p, 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)
    acc = jnp.einsum("bhst,bhtd->bhsd", p, v.astype(jnp.float32))
    return m_safe, l, acc


def _merge(m1, l1, acc1, m2, l2, acc2):
    """Merge two online-softmax partials."""
    m = jnp.maximum(m1, m2)
    a1 = jnp.exp(m1 - m)
    a2 = jnp.exp(m2 - m)
    return m, a1 * l1 + a2 * l2, a1 * acc1 + a2 * acc2


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    axis: str = "dp",
) -> jnp.ndarray:
    """Causal attention with Q/K/V sequence-sharded over ``axis``.

    q/k/v: (B, H, S, D) global shape, S divisible by the axis size.
    Returns (B, H, S, D) with the same sharding.
    """
    n = mesh.shape[axis]
    B, H, S, D = q.shape
    if S % n:
        raise ValueError(f"sequence {S} not divisible by ring size {n}")
    shard = S // n
    scale = 1.0 / (D**0.5)
    seq_sharding = NamedSharding(mesh, P(None, None, axis, None))

    def local(q, k, v):
        idx = jax.lax.axis_index(axis)
        q_off = idx * shard

        m, l, acc = _block_attn(q, k, v, q_off, idx * shard, scale)

        def body(r, carry):
            k_cur, v_cur, m, l, acc = carry
            # Pass K/V to the next device; receive from the previous one.
            # The ppermute runs unconditionally (every device must join the
            # collective); only the attention math is skipped.
            perm = [(i, (i + 1) % n) for i in range(n)]
            k_cur = jax.lax.ppermute(k_cur, axis, perm)
            v_cur = jax.lax.ppermute(v_cur, axis, perm)
            src = (idx - r) % n  # owner of the shard we just received

            def attend(operand):
                k_in, v_in, m, l, acc = operand
                m2, l2, acc2 = _block_attn(
                    q, k_in, v_in, q_off, src * shard, scale
                )
                return _merge(m, l, acc, m2, l2, acc2)

            # Shards owned by later devices are entirely in this Q shard's
            # future: every score would be masked, so skip the two einsums
            # (on average (n-1)/2 steps per device — half the ring FLOPs).
            m, l, acc = jax.lax.cond(
                src <= idx,
                attend,
                lambda operand: (operand[2], operand[3], operand[4]),
                (k_cur, v_cur, m, l, acc),
            )
            return k_cur, v_cur, m, l, acc

        _, _, m, l, acc = jax.lax.fori_loop(1, n, body, (k, v, m, l, acc))
        return (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)

    mapped = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(None, None, axis, None),) * 3,
        out_specs=P(None, None, axis, None),
    )
    q = jax.device_put(q, seq_sharding)
    k = jax.device_put(k, seq_sharding)
    v = jax.device_put(v, seq_sharding)
    return mapped(q, k, v)
