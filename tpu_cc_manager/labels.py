"""The node-label contract: desired state, actual state, readiness.

This is the TPU mapping of the reference's label state machine (SURVEY.md §5;
reference main.py:62, gpu_operator_eviction.py:23-38). The protocol is kept
intact — desired/actual state carried on node labels, drain via a label pause
protocol — with TPU-native names and one TPU-specific mode value.

Mode semantics (reference modes at main.py:214-296):

=========  =====================================================================
``on``     CC enabled for the node's TPU chips (reference ``on``).
``off``    CC disabled (reference ``off``).
``devtools``  CC enabled with a debug attestation policy AND a debug runtime
           configuration. Policy side: quotes are fetched and logged but
           verification failures do not fail the reconcile. Backend side
           (tpudev/tpuvm.py): the staged runtime environment file carries
           debug/trace flags (``TPU_MIN_LOG_LEVEL=0``,
           ``TPU_STDERR_LOG_LEVEL=0``, vmodule tracing), committed by the
           runtime restart like any mode change — so a devtools runtime is
           *measurably* different (the env file is on the measured-paths
           list, hence a distinct attested runtime digest), mirroring the
           reference where devtools is a real hardware mode, not a label
           (main.py:214-263).
``slice``  Slice-wide CC across every host of a multi-host ICI domain, staged
           and committed with fabric atomicity. This is the TPU analogue of
           the reference's ``ppcie`` multi-GPU Protected-PCIe mode
           (main.py:265-426): a TPU slice connected by ICI is the analogue of
           the NVLink/NVSwitch fabric, so CC state must be toggled per-slice,
           not per-chip. ``ppcie`` is accepted as a deprecated input alias.
=========  =====================================================================
"""

from __future__ import annotations

import re

# --- Desired / actual / readiness labels (reference: nvidia.com/cc.mode,
# nvidia.com/cc.mode.state, nvidia.com/cc.ready.state).
CC_MODE_LABEL = "cloud.google.com/tpu-cc.mode"
CC_MODE_STATE_LABEL = "cloud.google.com/tpu-cc.mode.state"
CC_READY_STATE_LABEL = "cloud.google.com/tpu-cc.ready.state"

# Valid desired modes. Absent/empty label means "use the default".
MODE_ON = "on"
MODE_OFF = "off"
MODE_DEVTOOLS = "devtools"
MODE_SLICE = "slice"
VALID_MODES = (MODE_ON, MODE_OFF, MODE_DEVTOOLS, MODE_SLICE)

# Deprecated input aliases (accepted on the desired label, never written back).
MODE_ALIASES = {"ppcie": MODE_SLICE}

# Actual-state values: every valid mode plus "failed"
# (reference gpu_operator_eviction.py:268).
STATE_FAILED = "failed"

# Machine-readable failure reason, set alongside state=failed and cleared on
# any other state. No reference counterpart (the reference's only failure
# signal is the bare 'failed' value); added so operators can distinguish a
# misconfigured node (e.g. slice mode on non-slice hardware) from a
# transient device fault without scraping agent logs.
CC_FAILED_REASON_LABEL = "cloud.google.com/tpu-cc.failed.reason"

# Drained components: label key on the node -> pod app label selector value.
# Reference analogue: the five nvidia.com/gpu.deploy.* components and their
# app-label map (gpu_operator_eviction.py:23-38). The TPU set covers the GKE
# TPU stack: the device plugin that advertises google.com/tpu resources, the
# DRA driver, node metrics, the CC/workload validators.
DRAIN_COMPONENT_LABELS = {
    "google.com/tpu.deploy.device-plugin": "tpu-device-plugin",
    "google.com/tpu.deploy.dra-driver": "tpu-dra-driver",
    "google.com/tpu.deploy.metrics-agent": "tpu-metrics-agent",
    "google.com/tpu.deploy.sandbox-validator": "tpu-sandbox-validator",
    "google.com/tpu.deploy.workload-validator": "tpu-workload-validator",
}

# Slice membership, published by the agent after a successful reconcile;
# nodes of one multi-host ICI slice carry the same value. Consumed by the
# rolling orchestrator (group-by-slice) and multi-slice attestation.
SLICE_ID_LABEL = "cloud.google.com/tpu-slice-id"

# Quarantine: the terminal rung of the remediation ladder
# (ccmanager/remediation.py). A quarantined node carries this label (value
# "true"), a NoSchedule taint under the same key, and ready.state=false;
# the rolling orchestrator and pool attestation skip it, and the pool
# failure budget counts it. Cleared on probation lift or manual
# `tpu-cc-ctl unquarantine`.
QUARANTINED_LABEL = "cloud.google.com/tpu-cc.quarantined"
QUARANTINE_TAINT_KEY = "cloud.google.com/tpu-cc.quarantined"

# --- Centralized wire names (cclint surface contract) -----------------------
# Every cloud.google.com/tpu-cc.* / tpu-cc.gke.io key the agent writes or
# reads lives HERE; the owning modules re-export them so their public API
# is unchanged, and the cclint label-literal check (lint/surface.py) fails
# any new inline literal. One module owns the wire names: a renamed key is
# one diff hunk, not a grep across the thread soup.

# Slice commit barrier markers (ccmanager/slicecoord.py): staged/commit
# markers carry "<mode>:<ts>", the fencing generation invalidates a round.
SLICE_STAGED_LABEL = "cloud.google.com/tpu-cc.slice.staged"
SLICE_COMMIT_LABEL = "cloud.google.com/tpu-cc.slice.commit"
SLICE_FENCE_LABEL = "cloud.google.com/tpu-cc.slice.fence"
SLICE_STAGED_GEN_LABEL = "cloud.google.com/tpu-cc.slice.staged-gen"
SLICE_COMMIT_GEN_LABEL = "cloud.google.com/tpu-cc.slice.commit-gen"

# Remediation-ladder persistence (ccmanager/remediation.py).
REMEDIATION_ANNOTATION = "cloud.google.com/tpu-cc.remediation"

# Fail-slow vetting (obs/failslow.py): "true" while peer-relative
# outlier vetting suspects the node of a gray failure — operator
# telemetry for the `ctl status` SUSPECT column, never control flow
# (the rollout record's journaled verdicts are authoritative for
# acting). Cleared when the peer-relative stats recover.
FAILSLOW_SUSPECT_LABEL = "cloud.google.com/tpu-cc.failslow.suspect"

# Crash-safe rollouts (ccmanager/rollout_state.py): the checkpointed
# record on the Lease, and the generation stamp on rolled nodes.
ROLLOUT_RECORD_ANNOTATION = "cloud.google.com/tpu-cc.rollout-record"
ROLLOUT_GEN_LABEL = "cloud.google.com/tpu-cc.rollout-gen"

# Surge rollouts (ccmanager/rolling.py): spares flip first behind this
# NoSchedule taint and are reclaimed on convergence.
SURGE_TAINT_KEY = "cloud.google.com/tpu-cc.surge"

# Spare pre-staging (zero-bounce flips, ccmanager/manager.py +
# rolling.py): the orchestrator (or an operator) writes the target mode
# into the PRESTAGE annotation; the agent runs the full journaled
# transition + compile warmup ahead of the wave, reports the truthful
# state label, HOLDS there (the prestage annotation suppresses the
# revert a desired!=state reconcile would otherwise perform), and
# publishes a JSON status record — {"mode","prior","seconds","ts"} — in
# the PRESTAGED annotation. The later desired-mode write then converges
# in ~drain+readmit time via the idempotent re-attest path. Deleting the
# PRESTAGE annotation aborts the hold (the agent reverts to the desired
# mode on its next reconcile).
PRESTAGE_ANNOTATION = "cloud.google.com/tpu-cc.prestage"
PRESTAGED_ANNOTATION = "cloud.google.com/tpu-cc.prestaged"

# Multi-slice attestation (ccmanager/multislice.py): summary quote,
# full quote payload, and the verifier-challenge nonce.
QUOTE_ANNOTATION = "cloud.google.com/tpu-cc.attestation"
QUOTE_FULL_ANNOTATION = "cloud.google.com/tpu-cc.quote"
CHALLENGE_ANNOTATION = "cloud.google.com/tpu-cc.challenge"

# Preemption handoff record (ccmanager/manager.py): published by the
# departing agent, consumed by the replacement node's agent.
HANDOFF_ANNOTATION = "cloud.google.com/tpu-cc.handoff"

# Workload drain handshake (drain/handshake.py): drain request + deadline
# hint on the node; per-job ack annotations under the subscriber prefix.
DRAIN_REQUESTED_LABEL = "cloud.google.com/tpu-cc.drain"
DRAIN_DEADLINE_LABEL = "cloud.google.com/tpu-cc.drain.deadline-s"
DRAIN_SUBSCRIBER_PREFIX = "drain-subscriber.tpu-cc.gke.io/"

# Event → span-tree correlation (ccmanager/manager.py _emit_node_event),
# and the node annotation the agent republishes its LAST reconcile's
# trace id into (ctl status surfaces it as the TRACE column, so an
# operator can jump from status straight to /tracez?trace_id=...).
TRACE_ID_ANNOTATION = "tpu-cc.gke.io/trace-id"

# Cross-process trace stitching (ccmanager/rolling.py → manager.py): the
# orchestrator stamps "<trace_id>.<span_id>" of its rollout trace into
# every desired-mode patch; the node agent adopts it as the REMOTE
# parent of its reconcile root span, so /tracez renders one causal tree
# from `ctl rollout` down through each node's drain/reset/smoke
# (obs/trace.py format_parent/parse_parent).
ROLLOUT_TRACE_LABEL = "cloud.google.com/tpu-cc.rollout-trace"

# Pause protocol (reference gpu_operator_eviction.py:43-95):
#   'true'        -> PAUSED_VALUE
#   custom 'v'    -> 'v' + PAUSED_SUFFIX
#   'false' / ''  -> unchanged (user-disabled component)
#   already paused-> unchanged
# Unpausing inverts exactly.
PAUSED_VALUE = "paused-for-tpu-cc-mode-change"
PAUSED_SUFFIX = "_paused-for-tpu-cc-mode-change"


def canonical_mode(mode: str) -> str:
    """Map deprecated aliases onto canonical mode names (``ppcie``→``slice``)."""
    return MODE_ALIASES.get(mode, mode)


_LABEL_ILLEGAL = re.compile(r"[^A-Za-z0-9_.-]")


def label_safe(value: str, max_len: int = 63) -> str:
    """Coerce a string into a valid k8s label value (ASCII alnum/-/_/. and
    at most 63 chars; must start and end alphanumeric). ASCII explicitly:
    Python's ``isalnum`` admits unicode letters/digits ('À', '٣') that the
    apiserver's label regex rejects. The single shared sanitizer — every
    module writing derived label values (slice ids, failure reasons) must
    produce identical output for identical input."""
    cleaned = _LABEL_ILLEGAL.sub("-", value)[:max_len].strip("-_.")
    return cleaned or "unknown"


def ready_state_for(state: str) -> str:
    """Derive the readiness label value from the actual-state value.

    Reference (gpu_operator_eviction.py:275-288): on/ppcie -> "true",
    off -> "false", anything else -> "". Divergence, decided explicitly per
    SURVEY.md §8.4: the reference leaves ``devtools`` with an empty ready
    state; we report ``"debug"`` so schedulers can distinguish "CC up but in
    debug-attestation mode" from "unknown/failed".
    """
    if state in (MODE_ON, MODE_SLICE):
        return "true"
    if state == MODE_OFF:
        return "false"
    if state == MODE_DEVTOOLS:
        return "debug"
    return ""
