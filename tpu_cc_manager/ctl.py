"""tpu-cc-ctl: operator CLI for pool-level operations.

The reference has no pool tooling (its only entry point is the per-node
agent); this CLI drives the new coordination layers:

- ``rollout``  rolling CC reconfiguration across a pool
  (ccmanager/rolling.py; BASELINE.json configs[3]),
- ``attest``   cross-slice attestation verification
  (ccmanager/multislice.py; configs[4]),
- ``status``   one-line-per-node view of desired/actual/ready labels.

Usage: ``python -m tpu_cc_manager.ctl <command> ...`` or the
``tpu-cc-ctl`` console script.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys

from tpu_cc_manager.ccmanager.multislice import (
    PoolAttestationError,
    pool_report,
    verify_pool_attestation,
)
from tpu_cc_manager.ccmanager.rolling import (
    SLICE_ID_LABEL,
    SURGE_TAINT_KEY,
    RollingReconfigurator,
)
from tpu_cc_manager.kubeclient.api import node_labels
from tpu_cc_manager.kubeclient.rest import ClusterConfig, RestKube
from tpu_cc_manager.labels import (
    CC_MODE_LABEL,
    CC_MODE_STATE_LABEL,
    CC_READY_STATE_LABEL,
    VALID_MODES,
)
from tpu_cc_manager.utils.logging import setup_logging

log = logging.getLogger(__name__)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="tpu-cc-ctl")
    p.add_argument("--kubeconfig", default=None)
    p.add_argument("-d", "--debug", action="store_true")
    sub = p.add_subparsers(dest="command", required=True)

    r = sub.add_parser("rollout", help="rolling CC reconfiguration over a pool")
    r.add_argument("--selector", required=True, help="node label selector, e.g. pool=tpu")
    r.add_argument(
        "--mode", default=None,
        help=f"target mode: {VALID_MODES} (optional with --resume, which "
        "adopts the persisted record's mode)",
    )
    r.add_argument(
        "--max-unavailable", type=str, default=None,
        help="concurrent group budget (default 1; a resumed rollout "
        "inherits the record's value unless this flag is passed). With "
        "--regions, accepts per-region overrides — '2,r2=3' caps r2 at "
        "3 with every other region at 2",
    )
    r.add_argument("--node-timeout", type=float, default=600.0)
    r.add_argument("--continue-on-failure", action="store_true")
    r.add_argument(
        "--rollback-on-failure", action="store_true",
        help="on halt, revert already-converged groups to their prior "
        "desired mode (the failed group is left for the operator)",
    )
    r.add_argument(
        "--failure-budget", type=str, default=None,
        help="pool failure budget: halt (and refuse to start) when MORE "
        "than this many nodes are quarantined or already failed this "
        "rollout (pre-crash failures persist in the record) — a "
        "fleet-level circuit breaker (default: no budget). With "
        "--regions, accepts heterogeneous per-region budgets — "
        "'r1=2,r2=5' (every region must be named; the global budget is "
        "their sum, and a region halts alone at its own cap)",
    )
    r.add_argument(
        "--wave-shards", type=int, default=None,
        help="sharded rollout waves: run up to N concurrent sub-rollouts "
        "partitioned by zone (topology.kubernetes.io/zone; groups "
        "without a zone partition alone) under ONE failure budget and "
        "ONE resumable record — total in-flight disruption is "
        "wave-shards × max-unavailable (default 1: the classic strictly "
        "rolling single queue; a resume inherits the record's value)",
    )
    r.add_argument(
        "--surge", type=int, default=None,
        help="surge rollout: flip up to N spare nodes FIRST behind the "
        f"{SURGE_TAINT_KEY} NoSchedule taint "
        "(unschedulable-for-workloads for exactly their flip window), "
        "then reclaim them — the rolling waves migrate workloads onto "
        "already-flipped capacity, so measured pool unavailability stays "
        "<= max-unavailable throughout (default 0: no surge; a resume "
        "inherits the record's value)",
    )
    r.add_argument(
        "--prestage", action="store_true",
        help="zero-bounce spares: with --surge, arm the spares' "
        "pre-staging (surge taint + prestage annotation — each agent "
        "runs the full journaled flip + compile warmup ahead of the "
        "wave and holds) and await their records before opening the "
        "flip window, which then converges in ~drain+readmit time; "
        "spares already armed by --prestage-only flip instantly. "
        "Agents that never pre-stage fall back to the full flip after "
        "--prestage-timeout",
    )
    r.add_argument(
        "--prestage-only", action="store_true",
        help="arm + await spare pre-staging and EXIT without flipping "
        "anything (requires --surge N and --mode): run it while the "
        "pool is still serving at full capacity, then the later "
        "--surge --prestage rollout's spare window opens instantly. "
        "The surge taint is kept on armed spares until that rollout "
        "reclaims them",
    )
    r.add_argument(
        "--prestage-timeout", type=float, default=None,
        help="seconds to await the spares' pre-staged records before "
        "falling back to the full flip (default: --node-timeout)",
    )
    r.add_argument(
        "--prestage-continuous", action="store_true",
        help="whole-fleet zero-bounce: prestage upcoming REGULAR "
        "windows (wave N+1 arms while wave N flips) under a "
        "crash-journaled capacity ledger in the record (v7 — older "
        "binaries refuse it loudly). Concurrency is bounded by "
        "min(--prestage-knee-rps slack, max-unavailable); SLO burn "
        "pauses prestage (never the wave); a prestage failure "
        "downgrades that node to the full flip path. A resume of a "
        "ledgered record re-enables this automatically unless "
        "--no-prestage",
    )
    r.add_argument(
        "--no-prestage", action="store_true",
        help="degraded-mode escape hatch: disable continuous prestage "
        "even when resuming a record that carries a capacity ledger "
        "(its entries are invalidated and released on adoption; every "
        "node takes the full flip path — see docs/operations.md)",
    )
    r.add_argument(
        "--prestage-knee-rps", type=float, default=None,
        help="the serving pool's measured knee (hack/serve_bench.py "
        "--sweep): with --slo-source, the continuous-prestage headroom "
        "gate scrapes tpu_cc_serve_offered_rps and allows prestage "
        "only while offered load leaves whole nodes of slack under "
        "this knee (no knee or no source: allowance defaults to "
        "max-unavailable)",
    )
    r.add_argument(
        "--no-adopt", action="store_true",
        help="do NOT adopt nodes created mid-rollout (autoscaler "
        "scale-up) into a trailing wave; by default new selector-matching "
        "nodes receive the desired mode + generation label before the "
        "rollout reports done",
    )
    r.add_argument(
        "--no-informer", action="store_true",
        help="poll with full pool listings instead of the watch-driven "
        "informer cache (the pre-informer O(pool) behavior; the cache "
        "needs `watch nodes` RBAC, which the DaemonSet role grants)",
    )
    r.add_argument(
        "--resume", action="store_true",
        help="resume an interrupted rollout from the record checkpointed "
        "in the rollout lease (converged groups are never re-bounced; "
        "also auto-detected when an in-progress record matches this "
        "invocation)",
    )
    r.add_argument(
        "--abort", dest="abort_rollout", action="store_true",
        help="release the rollout lease and DISCARD the persisted record "
        "(the escape hatch for a dead orchestrator's leftovers; safe — "
        "node agents keep converging on whatever desired labels were "
        "already written). Refuses a LIVE holder unless --force is also "
        "given",
    )
    r.add_argument(
        "--force", action="store_true",
        help="with --abort: fence out a LIVE (wedged) holder — its next "
        "lease write is refused and it stops. Never use this to jump the "
        "queue past a healthy rollout",
    )
    r.add_argument(
        "--no-lease", action="store_true",
        help="run UNFENCED without the single-writer lease/record "
        "(legacy behavior: no crash resume, concurrent rollouts race)",
    )
    r.add_argument(
        "--lease-duration", type=float, default=None,
        help="rollout lease duration in seconds (default 15; a dead "
        "orchestrator's lease becomes claimable this long after its "
        "last renewal)",
    )
    r.add_argument(
        "--lease-namespace", default=None,
        help="namespace of the rollout lease (default: "
        "$CC_ROLLOUT_LEASE_NAMESPACE or tpu-operator)",
    )
    r.add_argument(
        "--flight-file", default=None,
        help="rollout flight-recorder JSONL path (default: a selector-"
        "derived file under $CC_FLIGHT_DIR, so a crash+--resume on the "
        "same host appends to the interrupted timeline; read it back "
        "with `rollout-timeline`)",
    )
    r.add_argument(
        "--no-flight", action="store_true",
        help="do not record the flight-recorder timeline",
    )
    r.add_argument(
        "--metrics-port", type=int, default=0,
        help="serve the orchestrator's /metrics + /rolloutz (live "
        "flight-recorder snapshot) on this port for the rollout's "
        "duration (0 = off)",
    )
    r.add_argument(
        "--slo-max-burn-rate", type=float, default=None,
        help="SLO-paced rollout: pause the next wave while the serving "
        "pool's error-budget burn rate exceeds this (1.0 = spending "
        "exactly as provisioned); sustained burn halts like "
        "--failure-budget. Requires --slo-source. A --resume inherits "
        "the record's persisted gate when these flags are omitted",
    )
    r.add_argument(
        "--slo-p99-target-ms", type=float, default=None,
        help="SLO-paced rollout: also pause while the pool's windowed "
        "p99 latency exceeds this many milliseconds",
    )
    r.add_argument(
        "--slo-window", type=float, default=None,
        help="which exported SLO window (seconds) the gate judges "
        "(default: the fastest the pool exports)",
    )
    r.add_argument(
        "--slo-max-pause", type=float, default=None,
        help="pause budget in seconds: SLO burn sustained past this "
        "halts the rollout (default 300; on --resume an omitted flag "
        "keeps the record's persisted value)",
    )
    r.add_argument(
        "--slo-source", default=None,
        help="URL of the serving pool's /metrics exposition the SLO "
        "gate polls at wave boundaries (tpu_cc_serve_slo_p99_seconds / "
        "tpu_cc_serve_error_budget_burn)",
    )
    r.add_argument(
        "--regions", default=None,
        help="federated rollout: comma-separated region names "
        "(topology.kubernetes.io/region label values). One regional "
        "orchestrator shard per region, each with its own rollout "
        "lease and its own regional slice of ONE federated record; "
        "--failure-budget and --max-unavailable are GLOBAL (spent "
        "across all regions via the CAS-fenced parent record) unless "
        "given per-region overrides (see their help). "
        "'r1=ctx1,r2=ctx2' drives each region through a named "
        "kubeconfig context — a real multi-cluster federation, with "
        "the parent record on the default cluster. "
        "--resume resumes every region's slice; --abort force-aborts "
        "the whole federation (live shards self-fence on their next "
        "parent sync)",
    )

    tl = sub.add_parser(
        "rollout-timeline",
        help="render a rollout's flight-recorder timeline (obs/flight.py)"
        ": every orchestrator decision in order — plan, waves, windows, "
        "per-node outcomes, budget charges, halts, resumes — plus the "
        "exactly-once reconstruction; the answer to 'why did wave 3 "
        "halt', after the fact and across a crash+--resume",
    )
    tl.add_argument(
        "--selector", default=None,
        help="pool selector the rollout used (derives the default "
        "flight-file path, like `rollout` does)",
    )
    tl.add_argument(
        "--file", dest="flight_file", default=None,
        help="read this flight-recorder JSONL file instead of the "
        "selector-derived default",
    )
    tl.add_argument(
        "--stitch", nargs="+", default=None, metavar="FILE",
        help="stitch N shard/region flight files into one federated "
        "timeline (lease-generation then timestamp ordering, exact "
        "cross-stream duplicates collapsed, torn tails tolerated per "
        "stream) — the offline twin of the fleet gateway's "
        "/fleetz?rollout=",
    )
    tl.add_argument(
        "--json", action="store_true", dest="as_json",
        help="print raw events + reconstruction as JSON",
    )
    tl.add_argument(
        "--trace", action="store_true",
        help="also render the stitched causal trace tree for the "
        "rollout's trace id, read from a span JSONL file (--spans; the "
        "CC_TRACE_FILE sink's format) — the offline twin of "
        "/tracez?trace_id=",
    )
    tl.add_argument(
        "--spans", default=None,
        help="span JSONL file (CC_TRACE_FILE format) to stitch --trace "
        "from; agents' and the orchestrator's sinks can be concatenated",
    )

    a = sub.add_parser("attest", help="verify cross-slice attestation coherence")
    a.add_argument("--selector", required=True)
    a.add_argument("--mode", required=True)
    a.add_argument("--slices", type=int, default=None, help="expected slice count")
    a.add_argument("--max-age", type=float, default=3600.0)
    a.add_argument(
        "--allow-fake", action="store_true",
        help="admit fake-platform quotes (HMAC, shared test key) — only "
        "for pools running the fake device layer",
    )
    a.add_argument(
        "--no-verify-signatures", action="store_true",
        help="digest-labels-only check (r4 behavior): trusts node-patch "
        "RBAC instead of platform signatures",
    )
    a.add_argument(
        "--challenge", action="store_true",
        help="challenged re-attestation: publish a fresh per-node nonce, "
        "wait for each agent to re-quote bound to it, then verify — a "
        "replayed quote that passes every signature check fails this "
        "path (without it, freshness rests on token exp only)",
    )
    a.add_argument(
        "--challenge-timeout", type=float, default=30.0,
        help="seconds to wait for agents to answer the challenge before "
        "verifying (unanswered nodes then fail verification)",
    )

    s = sub.add_parser("status", help="per-node CC state table")
    s.add_argument("--selector", required=True)
    s.add_argument(
        "--lease-namespace", default=None,
        help="where to look for the rollout lease (default: "
        "$CC_ROLLOUT_LEASE_NAMESPACE or tpu-operator) — pass the same "
        "value the rollout used or its ROLLOUT line stays invisible",
    )

    q = sub.add_parser(
        "quarantine",
        help="manually quarantine a node: NoSchedule taint + "
        "cc.quarantined label + ready.state=false; rollouts and pool "
        "attestation skip it (ccmanager/remediation.py)",
    )
    q.add_argument("--node", required=True)
    q.add_argument(
        "--reason", default="operator",
        help="recorded in the remediation annotation and node event",
    )

    uq = sub.add_parser(
        "unquarantine",
        help="lift a quarantine: remove the taint + label, restore "
        "ready.state from the current mode.state, reset the ladder",
    )
    uq.add_argument("--node", required=True)
    uq.add_argument("--reason", default="operator")

    jn = sub.add_parser(
        "journal",
        help="show a node's live intent journal (open hardware-transition "
        "intents, deferred label patches, last replay outcome) by reading "
        "the agent's /journalz debug endpoint — the first stop when a "
        "node rode out an apiserver outage (ccmanager/intent_journal.py)",
    )
    jn.add_argument("--node", default=None, help="node whose agent to query")
    jn.add_argument(
        "--port", type=int,
        default=int(os.environ.get("CC_METRICS_PORT") or 0) or 9099,
        help="agent metrics/debug port (default: $CC_METRICS_PORT or 9099)",
    )
    jn.add_argument(
        "--url", default=None,
        help="query this /journalz URL directly instead of resolving the "
        "node's address through the apiserver",
    )
    jn.add_argument(
        "--json", action="store_true", dest="as_json",
        help="print the raw JSON payload instead of the summary view",
    )

    rb = sub.add_parser(
        "rbac-check",
        help="prove this identity holds every verb the agent needs "
        "(SelfSubjectAccessReview)",
    )
    rb.add_argument(
        "--namespace", default="tpu-operator",
        help="operator namespace for the pod-list check",
    )

    dsub = sub.add_parser(
        "drain-subscribe",
        help="sidecar: join the workload drain handshake without writing "
        "code — runs a checkpoint command when the node's manager "
        "requests a drain, then acks (drain/handshake.py)",
    )
    dsub.add_argument(
        "--job", required=True,
        help="job name for the subscriber label (label-sanitized)",
    )
    dsub.add_argument(
        "--node", default=None,
        help="node to watch (default: $NODE_NAME, the downward-API env "
        "every pod spec can set)",
    )
    dsub.add_argument(
        "--on-drain", required=True, metavar="CMD",
        help="shell command that durably checkpoints the job; exit 0 "
        "publishes the ack, non-zero is retried next poll",
    )
    dsub.add_argument(
        "--on-resume", default=None, metavar="CMD",
        help="optional shell command run when the drain request clears",
    )
    from tpu_cc_manager.drain.handshake import DEFAULT_ACK_POLL_INTERVAL_S

    dsub.add_argument(
        "--poll-interval", type=float,
        default=DEFAULT_ACK_POLL_INTERVAL_S,
        help="seconds between node polls during a drain "
        "(idle polls back off 5x)",
    )
    return p


def _parse_regions(spec: str) -> tuple[list[str], dict[str, str]]:
    """``--regions`` syntax: ``r1,r2`` (shards over one cluster, region-
    sliced selectors) or ``r1=ctx1,r2=ctx2`` (one kubeconfig context per
    region — a real multi-cluster federation). All-or-nothing on the
    contexts: half a federation silently sharing the local cluster is
    exactly the mixup the explicit form exists to prevent."""
    regions: list[str] = []
    contexts: dict[str, str] = {}
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        region, sep, ctx = entry.partition("=")
        region = region.strip()
        if not region:
            raise ValueError(f"--regions: bad entry {entry!r}")
        regions.append(region)
        if sep:
            if not ctx.strip():
                raise ValueError(
                    f"--regions: empty kubeconfig context for {region!r}"
                )
            contexts[region] = ctx.strip()
    if len(regions) != len(set(regions)):
        raise ValueError("--regions: duplicate region names")
    if contexts and len(contexts) != len(regions):
        missing = sorted(set(regions) - set(contexts))
        raise ValueError(
            "--regions: kubeconfig contexts must be given for EVERY "
            f"region or none (missing: {', '.join(missing)})"
        )
    return regions, contexts


def _parse_per_region_int(
    spec: str | None, flag: str, regions: list[str],
) -> tuple[int | None, dict[str, int]]:
    """Per-region integer flag syntax (``--failure-budget``,
    ``--max-unavailable`` under ``--regions``): a bare ``N`` is the
    default for every region, ``r=N`` overrides one. Returns
    ``(default, per_region)``; unknown region names are refused."""
    if spec is None:
        return None, {}
    default: int | None = None
    per: dict[str, int] = {}
    for entry in str(spec).split(","):
        entry = entry.strip()
        if not entry:
            continue
        region, sep, value = entry.partition("=")
        if not sep:
            if default is not None:
                raise ValueError(f"{flag}: more than one bare value")
            default = int(entry)
            continue
        region = region.strip()
        if region not in regions:
            raise ValueError(
                f"{flag}: unknown region {region!r} (not in --regions)"
            )
        if region in per:
            raise ValueError(f"{flag}: duplicate region {region!r}")
        per[region] = int(value)
    return default, per


def _plain_int_flag(value, flag: str) -> int | None:
    """Non-federated rollouts take these flags as plain integers; the
    per-region ``r=N`` syntax only means something under ``--regions``."""
    if value is None or isinstance(value, int):
        return value
    if "=" in str(value) or "," in str(value):
        raise ValueError(
            f"{flag}: per-region syntax ({value!r}) requires --regions"
        )
    return int(value)


def _abort_rollout(api, namespace: str | None, force: bool = False) -> int:
    """Release the rollout lease and discard its record. Safe against the
    pool: desired labels already written stay written and the node agents
    keep converging on them — aborting only removes the orchestrator-side
    lock + checkpoint. The lease OBJECT is kept (holder emptied via CAS,
    not deleted) so ``leaseTransitions`` — the fencing generation — stays
    monotonic across the abort. A LIVE holder is refused without
    ``--force``: aborting a healthy rollout opens exactly the concurrent-
    writer window the lease exists to close."""
    from tpu_cc_manager.ccmanager import rollout_state
    from tpu_cc_manager.kubeclient.api import KubeApiError

    namespace = namespace or rollout_state.lease_namespace()
    try:
        lease = api.get_lease(namespace, rollout_state.LEASE_NAME)
    except KubeApiError as e:
        if e.status == 404:
            print(f"no rollout lease in {namespace}; nothing to abort")
            return 0
        raise
    holder, alive = rollout_state.lease_holder_alive(lease)
    if alive and not force:
        log.error(
            "the rollout lease is held by a LIVE orchestrator (%s); "
            "aborting it would let two writers race the same pool. If it "
            "is wedged, re-run with --force (its next lease write is then "
            "refused and it stops); otherwise just wait", holder,
        )
        return 1
    rollout_state.release_lease(api, namespace, rollout_state.LEASE_NAME)
    print(
        f"rollout lease {namespace}/{rollout_state.LEASE_NAME} "
        + ("force-released (live holder fenced out)" if alive else "released")
        + "; persisted record discarded"
    )
    return 0


def cmd_rollout(api, args) -> int:
    from tpu_cc_manager.ccmanager import rollout_state
    from tpu_cc_manager.kubeclient.api import KubeApiError, is_lease_unsupported
    from tpu_cc_manager.labels import canonical_mode

    lease_namespace = getattr(args, "lease_namespace", None)
    if getattr(args, "regions", None):
        return _rollout_federated(api, args)
    if getattr(args, "abort_rollout", False):
        return _abort_rollout(
            api, lease_namespace, force=getattr(args, "force", False)
        )
    mode = canonical_mode(args.mode) if getattr(args, "mode", None) else None
    if mode is not None and mode not in VALID_MODES:
        # Fail BEFORE touching the lease: a typo'd mode must not leave a
        # held lease behind that blocks the corrected retry for a whole
        # lease duration.
        raise ValueError(f"invalid CC mode {mode!r} (valid: {VALID_MODES})")
    # Same pre-lease discipline for the flag syntax: the per-region
    # ``r=N`` form is only valid under --regions (handled above).
    args.failure_budget = _plain_int_flag(
        getattr(args, "failure_budget", None), "--failure-budget"
    )
    args.max_unavailable = _plain_int_flag(
        getattr(args, "max_unavailable", None), "--max-unavailable"
    )
    resume_requested = getattr(args, "resume", False)
    if resume_requested and getattr(args, "no_lease", False):
        # Contradictory: resume reads the record checkpointed in the
        # lease the other flag refuses to touch.
        raise ValueError("--resume cannot be combined with --no-lease")
    if getattr(args, "prestage_only", False):
        # Arm + await spare pre-staging and exit — writes only the surge
        # taint + prestage annotations (no desired-mode labels), is
        # idempotent, and touches no lease: the later --surge --prestage
        # rollout owns the fenced flip.
        if mode is None:
            raise ValueError("--prestage-only requires --mode")
        surge_n = getattr(args, "surge", None) or 0
        if surge_n <= 0:
            raise ValueError("--prestage-only requires --surge N")
        roller = RollingReconfigurator(
            api,
            args.selector,
            node_timeout_s=args.node_timeout,
            surge=surge_n,
            prestage=True,
            prestage_timeout_s=getattr(args, "prestage_timeout", None),
        )
        summary = roller.prestage_spares(mode)
        print(json.dumps(summary))
        return 0 if summary["ok"] else 1
    lease = None
    resume_record = None
    if not getattr(args, "no_lease", False):
        import os as _os
        import socket as _socket

        lease = rollout_state.RolloutLease(
            api,
            holder=f"{_socket.gethostname()}-{_os.getpid()}",
            namespace=lease_namespace,
            duration_s=(
                getattr(args, "lease_duration", None)
                or rollout_state.DEFAULT_LEASE_DURATION_S
            ),
        )
        try:
            record = lease.acquire()
        except rollout_state.LeaseHeld as e:
            log.error(
                "another rollout is already in progress: %s — wait for it "
                "to finish (or its lease to expire). Only if that holder "
                "is WEDGED: `tpu-cc-ctl rollout --abort --force` fences it "
                "out", e,
            )
            return 1
        except rollout_state.RolloutFenced as e:
            # An unreadable/corrupt checkpointed record (partial write,
            # manual edit): surface it cleanly with the escape hatch
            # instead of a traceback.
            log.error(
                "rollout record on the lease is unreadable (%s); "
                "`tpu-cc-ctl rollout --abort` discards it", e,
            )
            return 1
        except KubeApiError as e:
            if not is_lease_unsupported(e):
                log.error("could not acquire the rollout lease: %s", e)
                return 1
            if resume_requested:
                # An explicit --resume must not silently degrade into a
                # fresh unfenced rollout that re-plans from scratch.
                log.error(
                    "--resume: this client has no Lease support, so no "
                    "persisted record can be read"
                )
                return 2
            log.warning(
                "this client has no Lease support; running UNFENCED "
                "(no crash resume, concurrent rollouts race)"
            )
            lease = None
            record = None
        if record is not None:
            matches = record.selector == args.selector and (
                mode is None or record.mode == mode
            )
            if resume_requested:
                if record.status == rollout_state.RECORD_COMPLETE:
                    log.error(
                        "--resume: the persisted rollout already completed; "
                        "start a fresh rollout (or --abort to clear)"
                    )
                    lease.release()
                    return 2
                if not matches:
                    log.error(
                        "--resume: persisted record (mode=%s selector=%s) "
                        "does not match this invocation", record.mode,
                        record.selector,
                    )
                    lease.release()
                    return 2
                resume_record = record
            elif record.status == rollout_state.RECORD_IN_PROGRESS:
                # Auto-detect a dead orchestrator's unfinished rollout: a
                # matching invocation resumes it; a mismatched one must
                # not silently bulldoze a half-flipped pool.
                if matches:
                    log.warning(
                        "found an in-progress rollout record from a dead "
                        "orchestrator; resuming it (use --abort to discard)"
                    )
                    resume_record = record
                else:
                    log.error(
                        "an unfinished rollout record exists (mode=%s "
                        "selector=%s, %d/%d groups done) and does not match "
                        "this invocation — resume it with matching "
                        "arguments, or --abort to discard it",
                        record.mode, record.selector,
                        sum(1 for d in record.done.values() if d.get("ok")),
                        len(record.groups),
                    )
                    lease.release()
                    return 2
        elif resume_requested and lease is not None:
            log.error("--resume: no persisted rollout record found")
            lease.release()
            return 2
    federation_gate = None
    if resume_record is not None and resume_record.federation:
        # A regional slice of a MULTI-region federation: the successor
        # must re-attach to the parent record (global budget, fencing
        # generation) before touching a node — resuming it unfenced
        # would spend budget the siblings never see. Single-region
        # federated records persist as <=v4 and never reach here.
        from tpu_cc_manager.ccmanager import federation as federation_mod

        try:
            federation_gate = federation_mod.FederationGate.from_record_dict(
                api, resume_record.federation
            )
        except rollout_state.RolloutFenced as e:
            log.error(
                "resume: this record is a regional slice of a federated "
                "rollout and its parent refused the attachment (%s); "
                "`rollout --abort` discards the regional record", e,
            )
            lease.release()
            return 1
        log.warning(
            "resume: regional slice of a federated rollout (region %s of "
            "%d); re-attached to the parent record",
            federation_gate.region, federation_gate.regions_total,
        )
    failure_budget = getattr(args, "failure_budget", None)
    # None = flag omitted (the parser's default), distinguishable from an
    # explicit `--max-unavailable 1`.
    max_unavailable = getattr(args, "max_unavailable", None)
    wave_shards = getattr(args, "wave_shards", None)
    surge = getattr(args, "surge", None)
    if resume_record is not None:
        mode = resume_record.mode
        # The record also carries the dead orchestrator's settings: a
        # resume that wasn't explicitly re-parameterized must keep them —
        # above all the failure budget, or the fleet-level circuit
        # breaker (and its persisted pre-crash spend) silently vanishes
        # on resume. An explicitly-passed flag still wins.
        if failure_budget is None:
            failure_budget = resume_record.failure_budget
        if max_unavailable is None:
            max_unavailable = resume_record.max_unavailable
        if wave_shards is None:
            wave_shards = resume_record.wave_shards
        if surge is None:
            surge = resume_record.surge
    if max_unavailable is None:
        max_unavailable = 1
    if wave_shards is None:
        wave_shards = 1
    if surge is None:
        surge = 0
    # SLO-paced rollout: flags build the gate config. A --resume starts
    # from the gate persisted in the record (a latency-gated rollout
    # must stay latency-gated across a crash) and overlays any flags
    # explicitly passed — so `--resume --slo-max-pause 600` extends the
    # pause budget instead of being silently dropped.
    from tpu_cc_manager.ccmanager.rolling import SloGateConfig, metrics_gate

    slo_flag_values = {
        name: getattr(args, name, None)
        for name in (
            "slo_max_burn_rate", "slo_p99_target_ms", "slo_window",
            "slo_max_pause", "slo_source",
        )
    }
    slo_flags_given = any(v is not None for v in slo_flag_values.values())
    slo_config = None
    if resume_record is not None and resume_record.slo_gate:
        slo_config = SloGateConfig.from_dict(resume_record.slo_gate)
        if slo_flag_values["slo_max_burn_rate"] is not None:
            slo_config.max_burn_rate = slo_flag_values["slo_max_burn_rate"]
        if slo_flag_values["slo_p99_target_ms"] is not None:
            slo_config.p99_target_ms = slo_flag_values["slo_p99_target_ms"]
        if slo_flag_values["slo_window"] is not None:
            slo_config.window_s = slo_flag_values["slo_window"]
        if slo_flag_values["slo_max_pause"] is not None:
            slo_config.max_pause_s = slo_flag_values["slo_max_pause"]
        if slo_flag_values["slo_source"] is not None:
            slo_config.source = slo_flag_values["slo_source"]
        log.warning(
            "resume: re-arming the persisted SLO gate (burn<=%s, "
            "p99<=%sms, source=%s)",
            slo_config.max_burn_rate, slo_config.p99_target_ms,
            slo_config.source,
        )
    elif slo_flags_given:
        slo_config = SloGateConfig(
            max_burn_rate=(
                slo_flag_values["slo_max_burn_rate"]
                if slo_flag_values["slo_max_burn_rate"] is not None
                else 1.0
            ),
            p99_target_ms=slo_flag_values["slo_p99_target_ms"],
            window_s=slo_flag_values["slo_window"],
            max_pause_s=(
                slo_flag_values["slo_max_pause"]
                if slo_flag_values["slo_max_pause"] is not None else 300.0
            ),
            source=slo_flag_values["slo_source"],
        )
        if not slo_config.source:
            if lease is not None:
                lease.release()
            raise ValueError(
                "SLO gate flags need --slo-source (the serving pool's "
                "/metrics URL the gate polls)"
            )
    slo_gate = None
    if slo_config is not None:
        if not slo_config.source:
            # A record persisted by an in-process gate (ServeHarness)
            # has no pollable source; resuming from ctl cannot rebuild
            # the callable — say so instead of silently ungating.
            if lease is not None:
                lease.release()
            raise ValueError(
                "persisted SLO gate has no metrics source; re-run with "
                "--slo-source (or --abort to discard the record)"
            )
        slo_gate = metrics_gate(slo_config)
    # Continuous prestage (record v7 capacity ledger): the explicit
    # flag, or inherited on --resume from a record that carries a
    # ledger — a ledgered rollout must stay ledgered across a crash
    # (its checkpointed entries need adoption), unless the operator
    # degrades it deliberately with --no-prestage.
    continuous_prestage = getattr(args, "prestage_continuous", False)
    if getattr(args, "no_prestage", False):
        if continuous_prestage:
            if lease is not None:
                lease.release()
            raise ValueError(
                "--prestage-continuous and --no-prestage are "
                "contradictory"
            )
        if (
            resume_record is not None
            and resume_record.ledger is not None
            and resume_record.ledger.entries
        ):
            log.warning(
                "resume: --no-prestage on a ledgered record — its %d "
                "prestage entr(ies) will be released and every node "
                "takes the full flip path",
                len(resume_record.ledger.entries),
            )
    elif (
        not continuous_prestage
        and resume_record is not None
        and resume_record.ledger is not None
    ):
        continuous_prestage = True
        log.warning(
            "resume: the record carries a capacity ledger (%d live "
            "entr(ies)); re-enabling continuous prestage "
            "(--no-prestage to degrade)",
            len(resume_record.ledger.entries),
        )
    prestage_knee_rps = getattr(args, "prestage_knee_rps", None)
    if prestage_knee_rps and not continuous_prestage:
        if lease is not None:
            lease.release()
        raise ValueError(
            "--prestage-knee-rps needs --prestage-continuous (or a "
            "--resume of a ledgered record)"
        )
    if (
        continuous_prestage and prestage_knee_rps
        and (slo_config is None or not slo_config.source)
    ):
        if lease is not None:
            lease.release()
        raise ValueError(
            "--prestage-knee-rps needs --slo-source (the serving "
            "pool's /metrics URL the headroom gate scrapes for "
            "tpu_cc_serve_offered_rps)"
        )
    if mode is None:
        if lease is not None:
            lease.release()
        raise ValueError("--mode is required (unless --resume)")
    # Flight recorder: on by default (an appended JSONL line per
    # decision costs nothing next to an apiserver round trip), at a
    # selector-derived path so a --resume finds the interrupted
    # timeline without flag plumbing.
    from tpu_cc_manager.obs import flight as flight_mod

    flight = None
    if not getattr(args, "no_flight", False):
        flight = flight_mod.FlightRecorder(
            getattr(args, "flight_file", None)
            or flight_mod.flight_path_for(args.selector),
            generation=lease.generation if lease is not None else None,
        )
        if lease is not None:
            flight.record(
                flight_mod.EVENT_LEASE_ACQUIRED,
                holder=lease.holder,
                resumed=resume_record is not None or None,
            )
    metrics_server = None
    metrics_port = getattr(args, "metrics_port", 0)
    if lease is not None:
        lease.start_renewer()
    informer = None
    try:
        # Inside the try on purpose (metrics server AND informer): a
        # bind failure (port in use) or a client whose watch connect
        # raises eagerly (not the lazy "unsupported" probe) must hit
        # the BaseException lease-release below — failing BEFORE the
        # try would strand a held lease with the renewer still running,
        # and every later invocation would be refused with LeaseHeld
        # until the process dies.
        if metrics_port:
            from tpu_cc_manager.ccmanager.metrics_server import (
                start_metrics_server,
            )
            from tpu_cc_manager.utils import metrics as metrics_mod

            metrics_server = start_metrics_server(
                metrics_port, metrics_mod.REGISTRY, flight=flight,
            )
        if not getattr(args, "no_informer", False):
            from tpu_cc_manager.ccmanager.informer import NodeInformer
            from tpu_cc_manager.kubeclient.api import (
                is_pool_watch_unsupported,
            )

            try:
                informer = NodeInformer(api, args.selector).start()
            except KubeApiError as e:
                if not is_pool_watch_unsupported(e):
                    raise
                log.warning(
                    "this client has no pool-watch support; the rollout "
                    "falls back to O(pool) polling listings"
                )
                informer = None
        headroom_gate = None
        if continuous_prestage and prestage_knee_rps:
            # Whole-node slack under the measured knee, judged from the
            # pool's live offered-rate gauge. The node count is the
            # live selector population (a gate call is one scrape; the
            # count is re-read so autoscaling doesn't skew the slack).
            from tpu_cc_manager.ccmanager.rolling import (
                headroom_gate_from_source,
            )

            n_nodes = max(1, len(api.list_nodes(args.selector)))
            headroom_gate = headroom_gate_from_source(
                slo_config.source, prestage_knee_rps, n_nodes,
            )
        roller = RollingReconfigurator(
            api,
            args.selector,
            max_unavailable=max_unavailable,
            node_timeout_s=args.node_timeout,
            continue_on_failure=args.continue_on_failure,
            rollback_on_failure=args.rollback_on_failure,
            failure_budget=failure_budget,
            lease=lease,
            resume_record=resume_record,
            informer=informer,
            wave_shards=wave_shards,
            surge=surge,
            prestage=getattr(args, "prestage", False),
            prestage_timeout_s=getattr(args, "prestage_timeout", None),
            continuous_prestage=continuous_prestage,
            headroom_gate=headroom_gate,
            adopt_new_nodes=not getattr(args, "no_adopt", False),
            flight=flight,
            slo_gate=slo_gate,
            slo_config=slo_config,
            federation=federation_gate,
        )
        result = roller.rollout(mode)
    except rollout_state.RolloutFenced as e:
        log.error(
            "rollout fenced out mid-flight (%s); a successor owns the pool "
            "now — this process wrote nothing after losing the lease", e,
        )
        if flight is not None:
            flight.record(flight_mod.EVENT_FENCED, error=str(e))
        return 1
    except BaseException:
        # Any unexpected failure (usage error, apiserver crash mid-plan,
        # Ctrl-C) must not strand a held lease that blocks the corrected
        # retry for a whole lease duration; the checkpointed record (if
        # any) survives the release for --resume.
        if lease is not None:
            lease.release()
        raise
    finally:
        if informer is not None:
            informer.stop()
        if lease is not None:
            lease.stop_renewer()
        if metrics_server is not None:
            metrics_server.shutdown()
    if lease is not None:
        # A finished rollout clears its record (nothing to resume); a
        # failed/halted one keeps it so `--resume` can pick up after the
        # operator intervenes — either way the lease itself is released
        # so the next orchestrator need not wait out the duration.
        lease.release(clear_record=result.ok)
    print(json.dumps(result.summary()))
    return 0 if result.ok else 1


def _abort_federated(
    api, store, regions, region_apis, lease_namespace,
    federation_mod, rollout_state,
) -> int:
    """``rollout --regions ... --abort``: discard the parent record (live
    shards self-fence at their next sync) and force-release every
    regional lease. Partition-hardened on purpose: a corrupt parent is
    entombed, not a traceback, and a transport error against the parent
    plane must NOT strand the regional leases — they are released
    regardless, each on its own cluster when per-region contexts are in
    play."""
    from tpu_cc_manager.kubeclient.api import KubeApiError

    known_regions = set(regions)
    aborted = None
    unreadable = False
    parent_error: Exception | None = None
    try:
        parent = store.load()
    except federation_mod.ParentUnreadable as e:
        log.warning(
            "--abort --regions: parent record unreadable (%s); "
            "discarding it", e,
        )
        parent = None
        unreadable = True
    except KubeApiError as e:
        parent = None
        parent_error = e
    if parent is not None:
        known_regions |= set(parent.regions)
    if parent is None and not unreadable and parent_error is None:
        log.error("--abort --regions: no federated parent record")
        return 1
    if parent_error is None:
        try:
            aborted = store.abort()
        except KubeApiError as e:
            parent_error = e
    released: list[str] = []
    for region in sorted(known_regions):
        try:
            rollout_state.release_lease(
                region_apis.get(region, api),
                lease_namespace or rollout_state.lease_namespace(),
                name=federation_mod.regional_lease_name(region),
            )
            released.append(region)
        except KubeApiError as e:
            log.warning(
                "--abort --regions: could not release the %s regional "
                "lease (%s); it expires on its own after the lease "
                "duration", region, e,
            )
    if parent_error is not None:
        log.error(
            "--abort --regions: the parent plane is unreachable (%s). "
            "Regional leases released: %s. Re-run --abort once the "
            "parent apiserver is back so live shards fence at their "
            "next sync", parent_error, ", ".join(released) or "none",
        )
        return 1
    if aborted is None:
        log.error("--abort --regions: abort did not complete")
        return 1
    log.warning(
        "federated rollout aborted (generation now %d); every live "
        "shard is fenced at its next parent sync", aborted.generation,
    )
    return 0


def _rollout_federated(api, args) -> int:
    """``rollout --regions r1,r2,...``: one regional orchestrator shard
    per region (own lease, own flight file, own regional slice of the
    pool via the topology region label), federated under ONE parent
    record carrying the global plan digest and the single global
    failure budget / max-unavailable. Shards run as threads here; at
    fleet scale each shard is its own process against its own regional
    apiserver (hack/scale_bench.py --federation) — the parent-record
    protocol is identical."""
    import os as _os
    import socket as _socket
    import threading as _threading

    from tpu_cc_manager.ccmanager import federation as federation_mod
    from tpu_cc_manager.ccmanager import rollout_state
    from tpu_cc_manager.labels import canonical_mode
    from tpu_cc_manager.obs import flight as flight_mod

    regions, region_contexts = _parse_regions(args.regions)
    if getattr(args, "no_lease", False):
        raise ValueError(
            "--regions cannot run --no-lease: the federation IS the "
            "fencing hierarchy"
        )
    lease_namespace = getattr(args, "lease_namespace", None)
    # Per-region kubeconfig contexts: each shard drives ITS cluster while
    # the parent record stays on the default one — the coordination plane
    # and the data planes are different apiservers, which is exactly the
    # partition SCALE_r04 drills.
    region_apis: dict[str, object] = {}
    if region_contexts:
        from tpu_cc_manager.kubeclient.rest import ClusterConfig, RestKube

        for region, ctx in region_contexts.items():
            region_apis[region] = RestKube(
                ClusterConfig.load(args.kubeconfig, context=ctx)
            )
    store = federation_mod.ParentStore(api, namespace=lease_namespace)
    if getattr(args, "abort_rollout", False):
        return _abort_federated(
            api, store, regions, region_apis, lease_namespace,
            federation_mod, rollout_state,
        )
    mode = canonical_mode(args.mode) if getattr(args, "mode", None) else None
    if mode is not None and mode not in VALID_MODES:
        raise ValueError(f"invalid CC mode {mode!r} (valid: {VALID_MODES})")
    resume_requested = getattr(args, "resume", False)
    fb_default, region_budgets = _parse_per_region_int(
        getattr(args, "failure_budget", None), "--failure-budget", regions
    )
    if region_budgets and fb_default is not None:
        # '3,r2=5' is ambiguous — is the global budget 3, or the sum?
        # Heterogeneous budgets name every region; the global is their
        # sum by construction.
        raise ValueError(
            "--failure-budget: cannot mix a bare global value with "
            "per-region budgets"
        )
    if region_budgets and set(region_budgets) != set(regions):
        missing = sorted(set(regions) - set(region_budgets))
        raise ValueError(
            "--failure-budget: per-region budgets must name EVERY "
            f"region (missing: {', '.join(missing)})"
        )
    failure_budget = (
        sum(region_budgets.values()) if region_budgets else fb_default
    )
    mu_default, region_max_unavailable = _parse_per_region_int(
        getattr(args, "max_unavailable", None), "--max-unavailable", regions
    )
    max_unavailable = mu_default
    flags_budget_given = getattr(args, "failure_budget", None) is not None
    flags_mu_given = getattr(args, "max_unavailable", None) is not None
    if resume_requested:
        existing = store.load()
        if existing is None:
            log.error("--resume --regions: no federated parent record")
            return 2
        # The parent carries the dead federation's settings; explicit
        # flags still win (same inheritance rule as a regional resume).
        mode = mode or existing.mode
        if not flags_budget_given:
            failure_budget = existing.failure_budget
            region_budgets = dict(existing.region_budgets)
        if not flags_mu_given:
            max_unavailable = existing.max_unavailable
            region_max_unavailable = dict(existing.region_max_unavailable)
    if mode is None:
        raise ValueError("--mode is required (unless --resume)")
    if max_unavailable is None:
        max_unavailable = 1
    parent = store.initialize(
        federation_mod.ParentRecord.fresh(
            mode, args.selector, regions,
            max_unavailable=max_unavailable,
            failure_budget=failure_budget,
            region_budgets=region_budgets or None,
            region_max_unavailable=region_max_unavailable or None,
        ),
        resume=resume_requested,
    )
    results: dict[str, object] = {}
    errors: dict[str, BaseException] = {}
    flight_files: dict[str, str] = {}

    def run_region(region: str) -> None:
        rapi = region_apis.get(region, api)
        # With a per-region cluster the WHOLE pool there belongs to the
        # region — slicing by the topology label would select nothing on
        # clusters that don't stamp it.
        regional_selector = (
            args.selector if region in region_apis
            else federation_mod.regional_selector(args.selector, region)
        )
        lease = rollout_state.RolloutLease(
            rapi,
            holder=f"{_socket.gethostname()}-{_os.getpid()}-{region}",
            namespace=lease_namespace,
            name=federation_mod.regional_lease_name(region),
            duration_s=(
                getattr(args, "lease_duration", None)
                or rollout_state.DEFAULT_LEASE_DURATION_S
            ),
        )
        try:
            record = lease.acquire()
        except (rollout_state.LeaseHeld, rollout_state.RolloutFenced) as e:
            log.error("region %s: cannot acquire regional lease: %s",
                      region, e)
            results[region] = None
            return
        resume_record = None
        if record is not None and (
            record.status == rollout_state.RECORD_IN_PROGRESS
            or (resume_requested
                and record.status == rollout_state.RECORD_HALTED)
        ):
            fed = record.federation or {}
            if fed.get("digest") and fed["digest"] != parent.digest:
                log.error(
                    "region %s: regional record belongs to a different "
                    "federation (digest %s != %s); abort it first",
                    region, fed["digest"], parent.digest,
                )
                lease.release()
                results[region] = None
                return
            resume_record = record
        gate = federation_mod.FederationGate(store, region)
        try:
            gate.attach(parent)
        except rollout_state.RolloutFenced as e:
            log.error(
                "region %s: parent refused the attachment (%s)", region, e,
            )
            lease.release()
            results[region] = None
            return
        flight = None
        if not getattr(args, "no_flight", False):
            flight = flight_mod.FlightRecorder(
                getattr(args, "flight_file", None)
                and f"{args.flight_file}.{region}"
                or flight_mod.flight_path_for(regional_selector),
                generation=lease.generation,
            )
            flight_files[region] = flight.path
            flight.record(
                flight_mod.EVENT_LEASE_ACQUIRED, holder=lease.holder,
                region=region, resumed=resume_record is not None or None,
            )
        lease.start_renewer()
        result = None
        try:
            roller = RollingReconfigurator(
                rapi,
                regional_selector,
                max_unavailable=region_max_unavailable.get(
                    region, max_unavailable
                ),
                node_timeout_s=args.node_timeout,
                continue_on_failure=args.continue_on_failure,
                rollback_on_failure=args.rollback_on_failure,
                # The GLOBAL budget: a region's own cap (region_budgets)
                # is enforced by the gate at every parent sync, so one
                # blown region halts alone while the federation's total
                # spend still stops everyone.
                failure_budget=failure_budget,
                lease=lease,
                resume_record=resume_record,
                flight=flight,
                federation=gate,
            )
            result = roller.rollout(mode)
            results[region] = result
        except rollout_state.RolloutFenced as e:
            log.error(
                "region %s: shard fenced out mid-flight (%s); it wrote "
                "nothing after losing its fence", region, e,
            )
            if flight is not None:
                flight.record(
                    flight_mod.EVENT_FENCED, error=str(e), region=region
                )
            results[region] = None
        except BaseException as e:  # noqa: BLE001  # cclint: crash-ok(shard thread trampoline: the exception is stashed in `errors` and re-raised verbatim in the caller after join — a modeled SIGKILL still escapes through that re-raise)
            errors[region] = e
            results[region] = None
        finally:
            lease.stop_renewer()
            lease.release(
                clear_record=result is not None and result.ok
            )

    threads = [
        _threading.Thread(
            target=run_region, args=(region,),
            name=f"federation-{region}", daemon=True,
        )
        for region in regions
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        region, error = sorted(errors.items())[0]
        log.error("region %s shard died: %s", region, error)
        raise error
    final = store.load()
    ok = (
        final is not None
        and final.status == federation_mod.PARENT_COMPLETE
        and all(
            getattr(r, "ok", False) for r in results.values()
        )
    )
    if final is not None:
        print(federation_mod.describe_parent(final), file=sys.stderr)
    print(json.dumps({
        "ok": ok,
        "mode": mode,
        "regions": {
            region: (r.summary() if r is not None else None)
            for region, r in sorted(results.items())
        },
        "parent_status": final.status if final is not None else None,
        "budget_spend": len(final.budget_spend) if final is not None else 0,
        "flight_files": dict(sorted(flight_files.items())),
    }))
    return 0 if ok else 1


def cmd_rollout_timeline(api, args) -> int:
    """Render a rollout flight-recorder timeline (obs/flight.py): the
    raw decision stream in order plus the exactly-once reconstruction —
    and, with ``--trace``, the stitched orchestrator→agents span tree
    read from a CC_TRACE_FILE-format span JSONL."""
    from tpu_cc_manager.obs import flight as flight_mod

    stitch = getattr(args, "stitch", None)
    if stitch:
        events, torn = flight_mod.stitch_files(list(stitch))
        path = "+".join(stitch)
    else:
        path = getattr(args, "flight_file", None)
        if not path:
            if not getattr(args, "selector", None):
                raise ValueError(
                    "rollout-timeline: --selector (to derive the default "
                    "flight-file path) or --file is required"
                )
            path = flight_mod.flight_path_for(args.selector)
        events, torn = flight_mod.read_events(path)
    if not events:
        log.error("no flight-recorder events in %s", path)
        return 1
    if getattr(args, "as_json", False):
        print(json.dumps({
            "path": path,
            "torn_lines": torn,
            "events": events,
            "reconstruction": flight_mod.reconstruct(events),
        }, indent=1))
        return 0
    print(f"flight recorder: {path} ({len(events)} event(s))")
    print(flight_mod.render_timeline(events, torn=torn))
    if getattr(args, "trace", False):
        trace_ids = sorted({
            e["trace_id"] for e in events if e.get("trace_id")
        })
        print(f"\nrollout trace id(s): {', '.join(trace_ids) or '-'}")
        spans_path = getattr(args, "spans", None)
        if not spans_path:
            print(
                "(pass --spans <CC_TRACE_FILE jsonl> to render the "
                "stitched orchestrator->agent span tree offline, or "
                "query /tracez?trace_id=<id> on a live agent)"
            )
            return 0
        _print_stitched_trace(spans_path, trace_ids)
    return 0


def _print_stitched_trace(spans_path: str, trace_ids: list[str]) -> None:
    """Nest every span of the rollout's trace(s) from a span JSONL file
    (the CC_TRACE_FILE sink format; agent + orchestrator files can be
    concatenated) and print the tree — `ctl rollout` down through each
    node's drain/reset/smoke."""
    from tpu_cc_manager.obs.journal import Journal

    spans: list[dict] = []
    with open(spans_path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                s = json.loads(line)
            except ValueError:
                continue
            if isinstance(s, dict) and s.get("trace_id") in trace_ids:
                spans.append(s)
    if not spans:
        print(f"no spans for trace(s) {trace_ids} in {spans_path}")
        return
    journal = Journal(trace_file="")

    def render(node: dict, depth: int) -> None:
        attrs = node.get("attributes") or {}
        where = attrs.get("node") or attrs.get("group") or ""
        print(
            "  " * depth
            + f"{node['name']} ({node.get('seconds', 0):.3f}s, "
            f"{node.get('status')})" + (f" [{where}]" if where else "")
        )
        for child in sorted(
            node.get("children", []), key=lambda c: c.get("start_ts") or 0
        ):
            render(child, depth + 1)

    for root in sorted(
        journal.span_tree(spans), key=lambda r: r.get("start_ts") or 0
    ):
        render(root, 0)


def cmd_quarantine(api, args) -> int:
    from tpu_cc_manager.ccmanager.remediation import RemediationLadder

    ladder = RemediationLadder(api, args.node)
    if ladder.quarantined:
        print(f"{args.node}: already quarantined")
        return 0
    ladder.quarantine(reason=args.reason, manual=True)
    print(f"{args.node}: quarantined ({args.reason})")
    return 0


def cmd_unquarantine(api, args) -> int:
    from tpu_cc_manager.ccmanager.remediation import RemediationLadder

    ladder = RemediationLadder(api, args.node)
    ladder.unquarantine(reason=args.reason)
    print(f"{args.node}: quarantine lifted ({args.reason})")
    return 0


def cmd_attest(api, args) -> int:
    challenges = None
    if getattr(args, "challenge", False) and getattr(
        args, "no_verify_signatures", False
    ):
        # Contradictory: challenge binding is checked inside the
        # signed quote, which this flag says not to read — reporting
        # "(challenged re-attestation)" over a digest-labels-only
        # check would claim replay protection that never ran.
        raise ValueError(
            "--challenge cannot be combined with "
            "--no-verify-signatures (the challenge is verified "
            "inside the signed quote)"
        )
    # One informer serves every membership read below (challenge fan-out,
    # answer-await, report, verification) — the answer-await especially
    # used to cost one O(pool) listing per poll tick. Clients without
    # pool-watch support fall back to the legacy listing path.
    informer = None
    from tpu_cc_manager.ccmanager.informer import NodeInformer
    from tpu_cc_manager.kubeclient.api import (
        KubeApiError,
        is_pool_watch_unsupported,
    )

    try:
        informer = NodeInformer(api, args.selector).start()
    except KubeApiError as e:
        if not is_pool_watch_unsupported(e):
            raise
        informer = None
    try:
        if getattr(args, "challenge", False):
            from tpu_cc_manager.ccmanager import multislice

            challenges = multislice.issue_pool_challenges(
                api, args.selector, informer=informer
            )
            pending = multislice.await_challenge_answers(
                api, args.selector, challenges,
                timeout_s=getattr(args, "challenge_timeout", 30.0),
                informer=informer,
            )
            if pending:
                # Not fatal here: verification below fails the unanswered
                # nodes with the precise per-node problem.
                print(
                    f"WARN: challenge unanswered by: {', '.join(pending)}"
                )
        print(pool_report(api, args.selector, informer=informer))
        try:
            verify_pool_attestation(
                api, args.selector, args.mode,
                expected_slices=args.slices, max_age_s=args.max_age,
                allow_fake=getattr(args, "allow_fake", False),
                verify_signatures=not getattr(
                    args, "no_verify_signatures", False
                ),
                challenges=challenges,
                informer=informer,
            )
        except PoolAttestationError as e:
            print(f"FAIL: {e}")
            return 1
    finally:
        if informer is not None:
            informer.stop()
    print(
        "OK: pool attestation coherent"
        + (" (challenged re-attestation)" if challenges else "")
    )
    return 0


def _rollout_status_line(api, namespace: str | None = None) -> str | None:
    """The active/resumable rollout, from the lease + checkpointed record
    (None when there is no lease or the client lacks Lease support)."""
    from tpu_cc_manager.ccmanager import rollout_state
    from tpu_cc_manager.kubeclient.api import KubeApiError

    try:
        lease = api.get_lease(
            namespace or rollout_state.lease_namespace(),
            rollout_state.LEASE_NAME,
        )
    except KubeApiError:
        return None
    try:
        record = rollout_state.record_of_lease(lease)
    except rollout_state.RolloutFenced:
        record = "unreadable"  # still worth showing: --abort clears it
    if record is None and not (lease.get("spec") or {}).get("holderIdentity"):
        # A released, record-less lease is just the leftover object of a
        # finished rollout — nothing active or resumable to report.
        return None
    return rollout_state.describe_lease(lease)


def _prestage_status_line(api, namespace: str | None = None) -> str | None:
    """The capacity-ledger block for a ledgered record: per-state entry
    counts plus the charge/release balance — the first read of the
    continuous-prestage degraded-mode runbook (docs/operations.md
    "Continuous prestage & the capacity ledger")."""
    from tpu_cc_manager.ccmanager import rollout_state
    from tpu_cc_manager.kubeclient.api import KubeApiError

    try:
        lease = api.get_lease(
            namespace or rollout_state.lease_namespace(),
            rollout_state.LEASE_NAME,
        )
        record = rollout_state.record_of_lease(lease)
    except (KubeApiError, rollout_state.RolloutFenced):
        return None
    if (
        record is None
        or record.ledger is None
        or not record.ledger.touched()
    ):
        return None
    led = record.ledger
    by_state: dict[str, int] = {}
    for e in led.entries.values():
        s = str(e.get("state"))
        by_state[s] = by_state.get(s, 0) + 1
    line = (
        "PRESTAGE ledger: "
        f"{by_state.get(rollout_state.LEDGER_RESERVED, 0)} reserved, "
        f"{by_state.get(rollout_state.LEDGER_ARMED, 0)} armed, "
        f"{by_state.get(rollout_state.LEDGER_HELD, 0)} held; "
        f"charges={led.charges_total()} releases={led.releases_total()} "
        f"({'balanced' if led.balanced() else 'UNBALANCED'})"
    )
    if not led.balanced():
        line += " — resume with --no-prestage to drain"
    return line


def cmd_status(api, args) -> int:
    from tpu_cc_manager import labels as labels_mod
    from tpu_cc_manager.ccmanager import remediation as remediation_mod
    from tpu_cc_manager.ccmanager.rollout_state import ROLLOUT_GEN_LABEL
    from tpu_cc_manager.ccmanager.slicecoord import (
        SLICE_COMMIT_LABEL,
        SLICE_FENCE_LABEL,
        SLICE_STAGED_LABEL,
    )
    from tpu_cc_manager.drain import handshake
    from tpu_cc_manager.kubeclient.api import node_annotations
    from tpu_cc_manager.labels import CC_FAILED_REASON_LABEL

    rollout_line = _rollout_status_line(
        api, getattr(args, "lease_namespace", None)
    )
    if rollout_line:
        print(rollout_line)
        prestage_line = _prestage_status_line(
            api, getattr(args, "lease_namespace", None)
        )
        if prestage_line:
            print(prestage_line)
    # Federated rollouts: when a parent record exists, show the global
    # view (per-region status + escrow balances, global budget spend,
    # last-sync staleness) above the node table — the first thing to
    # read when one region looks stuck or the parent plane was dark.
    try:
        from tpu_cc_manager.ccmanager import federation as federation_mod

        try:
            parent = federation_mod.ParentStore(
                api, namespace=getattr(args, "lease_namespace", None)
            ).load()
        except federation_mod.ParentUnreadable as e:
            # A corrupt parent must read as an actionable line, not a
            # traceback or a silently missing block.
            print(
                "FEDERATION parent record UNREADABLE "
                f"({e}); `tpu-cc-ctl rollout --regions ... --abort` "
                "discards it"
            )
            parent = None
        if parent is not None:
            print(federation_mod.describe_parent(parent))
    except Exception as e:  # noqa: BLE001 - status stays best-effort
        log.debug("federated parent record unreadable: %s", e)
    rows = [
        f"{'NODE':<24} {'SLICE':<20} {'DESIRED':<10} {'STATE':<10} "
        f"{'READY':<6} {'SUSPECT':<8} {'TRACE':<17} NOTE"
    ]
    for node in api.list_nodes(args.selector):
        labels = node_labels(node)
        # The last reconcile's trace id, republished by the agent into
        # the node annotation — the jump-off point from status to
        # /tracez?trace_id=<TRACE> on that node's agent.
        trace = node_annotations(node).get(
            labels_mod.TRACE_ID_ANNOTATION
        ) or "-"
        # Transient barrier markers / failure reason / remediation ladder:
        # the things an operator staring at a stuck rollout needs first.
        notes = []
        ladder = remediation_mod.describe_annotation(
            node_annotations(node).get(remediation_mod.REMEDIATION_ANNOTATION)
        )
        if ladder:
            notes.append(ladder)
        if labels.get(SLICE_STAGED_LABEL):
            notes.append(f"barrier:staged={labels[SLICE_STAGED_LABEL]}")
        if labels.get(SLICE_COMMIT_LABEL):
            notes.append(f"barrier:commit={labels[SLICE_COMMIT_LABEL]}")
        if labels.get(SLICE_FENCE_LABEL):
            notes.append(f"barrier:fence-gen={labels[SLICE_FENCE_LABEL]}")
        if labels.get(CC_FAILED_REASON_LABEL):
            notes.append(f"reason={labels[CC_FAILED_REASON_LABEL]}")
        if labels.get(ROLLOUT_GEN_LABEL):
            notes.append(f"rollout-gen={labels[ROLLOUT_GEN_LABEL]}")
        # Zero-bounce spares: a spare whose warmup completed shows
        # PRESTAGED — while it HOLDS (desired != state) that explains
        # the deliberate divergence; after the wave landed it explains
        # why the wave opened instantly.
        raw = node_annotations(node).get(labels_mod.PRESTAGED_ANNOTATION)
        if raw:
            try:
                rec = json.loads(raw)
            except ValueError:
                rec = None
            if isinstance(rec, dict) and rec.get("mode"):
                held = labels.get(CC_MODE_LABEL) != rec.get("mode")
                notes.append(
                    f"PRESTAGED({rec['mode']},{rec.get('seconds')}s"
                    + (",holding)" if held else ")")
                )
        if node_annotations(node).get(labels_mod.PRESTAGE_ANNOTATION) and not raw:
            notes.append(
                "prestaging("
                + str(node_annotations(node).get(labels_mod.PRESTAGE_ANNOTATION))
                + ")"
            )
        token = handshake.request_token(
            labels.get(handshake.DRAIN_REQUESTED_LABEL)
        )
        if token is not None:
            subs = handshake.subscriber_labels_of(labels)
            # Same acceptance predicate as await_workload_acks: this
            # cycle's token OR the legacy bare ack (version-skewed job).
            accepted = (handshake.ack_value(token), handshake.ACKED)
            pending = sum(1 for v in subs.values() if v not in accepted)
            notes.append(
                f"drain:requested({len(subs) - pending}/{len(subs)} acked)"
            )
        # Fail-slow SUSPECT: published by the vetter (obs/failslow.py
        # publish_suspect_labels) while a node's peer-relative latency
        # deviates — green probes, gray service. Telemetry only; the
        # verdict journal in the rollout record is what acts.
        suspect = (
            "slow"
            if labels.get(labels_mod.FAILSLOW_SUSPECT_LABEL)
            else "-"
        )
        rows.append(
            f"{node['metadata']['name']:<24} "
            f"{labels.get(SLICE_ID_LABEL, '-'):<20} "
            f"{labels.get(CC_MODE_LABEL, '-'):<10} "
            f"{labels.get(CC_MODE_STATE_LABEL, '-'):<10} "
            f"{labels.get(CC_READY_STATE_LABEL, '-'):<6} "
            f"{suspect:<8} "
            f"{trace:<17} "
            f"{' '.join(notes) or '-'}"
        )
    print("\n".join(rows))
    return 0


def _node_debug_address(api, node_name: str) -> str:
    """The address `ctl journal` dials: InternalIP preferred (the debug
    port binds the pod/host network), Hostname as the fallback."""
    node = api.get_node(node_name)
    addresses = (node.get("status") or {}).get("addresses") or []
    by_type = {a.get("type"): a.get("address") for a in addresses}
    addr = (
        by_type.get("InternalIP")
        or by_type.get("ExternalIP")
        or by_type.get("Hostname")
    )
    if not addr:
        raise ValueError(
            f"node {node_name} exposes no address in status.addresses; "
            "pass --url http://<agent>:<port>/journalz directly"
        )
    return addr


def cmd_journal(api, args) -> int:
    """Show a node's live intent journal via the agent's /journalz debug
    endpoint (ccmanager/metrics_server.py)."""
    import urllib.request

    url = getattr(args, "url", None)
    if not url:
        if not getattr(args, "node", None):
            raise ValueError("journal: --node (or --url) is required")
        addr = _node_debug_address(api, args.node)
        url = f"http://{addr}:{args.port}/journalz"
    try:
        with urllib.request.urlopen(url, timeout=10) as resp:
            payload = json.loads(resp.read().decode())
    except (OSError, ValueError) as e:
        log.error("could not read %s: %s", url, e)
        return 1
    if getattr(args, "as_json", False):
        print(json.dumps(payload, indent=1))
        return 0
    if payload.get("enabled") is False:
        print("intent journal: DISABLED on this agent (CC_INTENT_JOURNAL=0)")
        return 0
    print(f"intent journal: {payload.get('path')} (seq={payload.get('seq')})")
    print(f"last desired mode: {payload.get('last_desired_mode') or '-'}")
    replay = payload.get("last_replay") or {}
    if replay:
        print(
            "last replay: %d record(s), %d torn byte(s) truncated"
            % (replay.get("records", 0), replay.get("truncated_bytes", 0))
        )
    intents = payload.get("open_intents") or []
    print(f"open intents: {len(intents)}")
    for i in intents:
        print(
            f"  {i.get('txn')}: kind={i.get('kind')} phase={i.get('phase')} "
            f"mode={i.get('mode', '-')} seq={i.get('seq')}"
        )
    pending = payload.get("pending_patches") or {}
    print(
        f"deferred label patches: {len(pending)} key(s) in "
        f"{payload.get('pending_patch_records', 0)} record(s)"
    )
    for key in sorted(pending):
        print(f"  {key} = {pending[key]!r}")
    return 0


def cmd_rbac_check(api, args) -> int:
    """Check every verb the agent uses (kubeclient/rest.py; the DaemonSet
    ClusterRole in deployments/manifests/daemonset.yaml must grant exactly
    these — including list nodes, which the slice barrier's peer discovery
    and the rolling orchestrator depend on)."""
    checks = [
        ("get", "nodes", None, True),
        ("list", "nodes", None, True),
        # `patch nodes` covers BOTH the metadata (labels/annotations)
        # writes and the quarantine taint write (spec.taints rides the
        # same resource + verb; ccmanager/remediation.py).
        ("patch", "nodes", None, True),
        ("watch", "nodes", None, True),
        ("list", "pods", args.namespace, True),
        # Events are best-effort (the agent degrades without them):
        # reported, but a denial doesn't fail the check. Node events live
        # in "default" (cluster-scoped involvedObject).
        ("create", "events", "default", False),
        # Rollout lease (ccmanager/rollout_state.py): get+create+update
        # carry acquisition, renewal and the checkpointed record; without
        # them `ctl rollout` degrades to an unfenced legacy rollout, so
        # they are reported required — a fleet relying on crash-safe
        # rollouts must not discover the gap mid-incident. delete is only
        # the operator's force-release (`rollout --abort`): optional.
        ("get", "leases", args.namespace, True),
        ("create", "leases", args.namespace, True),
        ("update", "leases", args.namespace, True),
        ("delete", "leases", args.namespace, False),
    ]
    ok = True
    for verb, resource, ns, required in checks:
        allowed = api.self_subject_access_review(verb, resource, namespace=ns)
        ok = ok and (allowed or not required)
        scope = f" (ns={ns})" if ns else ""
        verdict = "allowed" if allowed else (
            "DENIED" if required else "denied (optional)"
        )
        print(f"{verb:<6} {resource}{scope}: {verdict}")
    print("OK: RBAC sufficient" if ok else "FAIL: missing permissions")
    return 0 if ok else 1


def cmd_drain_subscribe(api, args) -> int:
    """Foreground sidecar process for the drain handshake: the pod's
    checkpoint command becomes the on_drain callback. SIGTERM/SIGINT
    unregister cleanly (pod shutdown must not leave a ghost subscriber
    the manager would wait on)."""
    import os
    import signal
    import subprocess

    from tpu_cc_manager.drain.handshake import DrainSubscriber

    node = args.node or os.environ.get("NODE_NAME")
    if not node:
        raise ValueError("--node or $NODE_NAME is required")

    current: dict = {"proc": None}

    def run_cmd(cmd: str) -> None:
        log.info("running: %s", cmd)
        proc = subprocess.Popen(cmd, shell=True)
        current["proc"] = proc
        try:
            rc = proc.wait()
        finally:
            current["proc"] = None
        if rc != 0:
            raise subprocess.CalledProcessError(rc, cmd)

    sub = DrainSubscriber(
        api, node, args.job,
        on_drain=lambda: run_cmd(args.on_drain),
        on_resume=(
            (lambda: run_cmd(args.on_resume)) if args.on_resume else None
        ),
        poll_interval_s=args.poll_interval,
    )
    args.subscriber = sub  # handle for callers/tests to stop() us

    def _shutdown(*_):
        # Also SIGTERM an in-flight checkpoint command: run() is blocked in
        # its wait, and the pod's grace period is ticking — if we merely set
        # the stop flag, kubelet SIGKILLs us before the unregister in
        # run()'s finally, leaving a ghost subscriber every future drain
        # would wait on.
        sub.stop(timeout_s=0)
        proc = current.get("proc")
        if proc is not None:
            proc.terminate()

    import threading

    if threading.current_thread() is threading.main_thread():
        # Signal handlers only exist on the main thread (tests drive this
        # command from a worker thread and stop via args.subscriber).
        for sig in (signal.SIGTERM, signal.SIGINT):
            signal.signal(sig, _shutdown)
    log.info(
        "drain subscriber %s watching node %s (ctrl-c / SIGTERM to leave)",
        sub.label, node,
    )
    sub.run()  # blocks; registers on entry, unregisters on the way out
    return 0


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    setup_logging(debug=args.debug)
    api = None
    if args.command != "rollout-timeline":
        # rollout-timeline reads only the local flight file (and an
        # optional span JSONL): no apiserver, no kubeconfig — and no
        # client-construction INFO line on stdout, which would corrupt
        # its --json output (logging goes to stdout by reference
        # parity).
        try:
            api = RestKube(ClusterConfig.load(args.kubeconfig))
        except Exception as e:  # noqa: BLE001 - any config failure is fatal here
            log.error("could not configure kubernetes client: %s", e)
            return 1
    from tpu_cc_manager.kubeclient.api import KubeApiError

    try:
        return {
            "rollout": cmd_rollout,
            "rollout-timeline": cmd_rollout_timeline,
            "attest": cmd_attest,
            "status": cmd_status,
            "quarantine": cmd_quarantine,
            "unquarantine": cmd_unquarantine,
            "journal": cmd_journal,
            "rbac-check": cmd_rbac_check,
            "drain-subscribe": cmd_drain_subscribe,
        }[args.command](api, args)
    except ValueError as e:
        log.error("usage error: %s", e)
        return 2
    except KubeApiError as e:
        log.error("apiserver error: %s", e)
        return 1


if __name__ == "__main__":
    sys.exit(main())
