"""tpu-cc-ctl: operator CLI for pool-level operations.

The reference has no pool tooling (its only entry point is the per-node
agent); this CLI drives the new coordination layers:

- ``rollout``  rolling CC reconfiguration across a pool
  (ccmanager/rolling.py; BASELINE.json configs[3]),
- ``attest``   cross-slice attestation verification
  (ccmanager/multislice.py; configs[4]),
- ``status``   one-line-per-node view of desired/actual/ready labels.

Usage: ``python -m tpu_cc_manager.ctl <command> ...`` or the
``tpu-cc-ctl`` console script.
"""

from __future__ import annotations

import argparse
import json
import logging
import sys

from tpu_cc_manager.ccmanager.multislice import (
    PoolAttestationError,
    pool_report,
    verify_pool_attestation,
)
from tpu_cc_manager.ccmanager.rolling import SLICE_ID_LABEL, RollingReconfigurator
from tpu_cc_manager.kubeclient.api import node_labels
from tpu_cc_manager.kubeclient.rest import ClusterConfig, RestKube
from tpu_cc_manager.labels import (
    CC_MODE_LABEL,
    CC_MODE_STATE_LABEL,
    CC_READY_STATE_LABEL,
    VALID_MODES,
)
from tpu_cc_manager.utils.logging import setup_logging

log = logging.getLogger(__name__)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="tpu-cc-ctl")
    p.add_argument("--kubeconfig", default=None)
    p.add_argument("-d", "--debug", action="store_true")
    sub = p.add_subparsers(dest="command", required=True)

    r = sub.add_parser("rollout", help="rolling CC reconfiguration over a pool")
    r.add_argument("--selector", required=True, help="node label selector, e.g. pool=tpu")
    r.add_argument("--mode", required=True, help=f"target mode: {VALID_MODES}")
    r.add_argument("--max-unavailable", type=int, default=1)
    r.add_argument("--node-timeout", type=float, default=600.0)
    r.add_argument("--continue-on-failure", action="store_true")
    r.add_argument(
        "--rollback-on-failure", action="store_true",
        help="on halt, revert already-converged groups to their prior "
        "desired mode (the failed group is left for the operator)",
    )
    r.add_argument(
        "--failure-budget", type=int, default=None,
        help="pool failure budget: halt (and refuse to start) when MORE "
        "than this many nodes are quarantined — a fleet-level circuit "
        "breaker (default: no budget)",
    )

    a = sub.add_parser("attest", help="verify cross-slice attestation coherence")
    a.add_argument("--selector", required=True)
    a.add_argument("--mode", required=True)
    a.add_argument("--slices", type=int, default=None, help="expected slice count")
    a.add_argument("--max-age", type=float, default=3600.0)
    a.add_argument(
        "--allow-fake", action="store_true",
        help="admit fake-platform quotes (HMAC, shared test key) — only "
        "for pools running the fake device layer",
    )
    a.add_argument(
        "--no-verify-signatures", action="store_true",
        help="digest-labels-only check (r4 behavior): trusts node-patch "
        "RBAC instead of platform signatures",
    )

    s = sub.add_parser("status", help="per-node CC state table")
    s.add_argument("--selector", required=True)

    q = sub.add_parser(
        "quarantine",
        help="manually quarantine a node: NoSchedule taint + "
        "cc.quarantined label + ready.state=false; rollouts and pool "
        "attestation skip it (ccmanager/remediation.py)",
    )
    q.add_argument("--node", required=True)
    q.add_argument(
        "--reason", default="operator",
        help="recorded in the remediation annotation and node event",
    )

    uq = sub.add_parser(
        "unquarantine",
        help="lift a quarantine: remove the taint + label, restore "
        "ready.state from the current mode.state, reset the ladder",
    )
    uq.add_argument("--node", required=True)
    uq.add_argument("--reason", default="operator")

    rb = sub.add_parser(
        "rbac-check",
        help="prove this identity holds every verb the agent needs "
        "(SelfSubjectAccessReview)",
    )
    rb.add_argument(
        "--namespace", default="tpu-operator",
        help="operator namespace for the pod-list check",
    )

    dsub = sub.add_parser(
        "drain-subscribe",
        help="sidecar: join the workload drain handshake without writing "
        "code — runs a checkpoint command when the node's manager "
        "requests a drain, then acks (drain/handshake.py)",
    )
    dsub.add_argument(
        "--job", required=True,
        help="job name for the subscriber label (label-sanitized)",
    )
    dsub.add_argument(
        "--node", default=None,
        help="node to watch (default: $NODE_NAME, the downward-API env "
        "every pod spec can set)",
    )
    dsub.add_argument(
        "--on-drain", required=True, metavar="CMD",
        help="shell command that durably checkpoints the job; exit 0 "
        "publishes the ack, non-zero is retried next poll",
    )
    dsub.add_argument(
        "--on-resume", default=None, metavar="CMD",
        help="optional shell command run when the drain request clears",
    )
    from tpu_cc_manager.drain.handshake import DEFAULT_ACK_POLL_INTERVAL_S

    dsub.add_argument(
        "--poll-interval", type=float,
        default=DEFAULT_ACK_POLL_INTERVAL_S,
        help="seconds between node polls during a drain "
        "(idle polls back off 5x)",
    )
    return p


def cmd_rollout(api, args) -> int:
    roller = RollingReconfigurator(
        api,
        args.selector,
        max_unavailable=args.max_unavailable,
        node_timeout_s=args.node_timeout,
        continue_on_failure=args.continue_on_failure,
        rollback_on_failure=args.rollback_on_failure,
        failure_budget=getattr(args, "failure_budget", None),
    )
    result = roller.rollout(args.mode)
    print(json.dumps(result.summary()))
    return 0 if result.ok else 1


def cmd_quarantine(api, args) -> int:
    from tpu_cc_manager.ccmanager.remediation import RemediationLadder

    ladder = RemediationLadder(api, args.node)
    if ladder.quarantined:
        print(f"{args.node}: already quarantined")
        return 0
    ladder.quarantine(reason=args.reason, manual=True)
    print(f"{args.node}: quarantined ({args.reason})")
    return 0


def cmd_unquarantine(api, args) -> int:
    from tpu_cc_manager.ccmanager.remediation import RemediationLadder

    ladder = RemediationLadder(api, args.node)
    ladder.unquarantine(reason=args.reason)
    print(f"{args.node}: quarantine lifted ({args.reason})")
    return 0


def cmd_attest(api, args) -> int:
    print(pool_report(api, args.selector))
    try:
        verify_pool_attestation(
            api, args.selector, args.mode,
            expected_slices=args.slices, max_age_s=args.max_age,
            allow_fake=getattr(args, "allow_fake", False),
            verify_signatures=not getattr(args, "no_verify_signatures", False),
        )
    except PoolAttestationError as e:
        print(f"FAIL: {e}")
        return 1
    print("OK: pool attestation coherent")
    return 0


def cmd_status(api, args) -> int:
    from tpu_cc_manager.ccmanager import remediation as remediation_mod
    from tpu_cc_manager.ccmanager.slicecoord import (
        SLICE_COMMIT_LABEL,
        SLICE_FENCE_LABEL,
        SLICE_STAGED_LABEL,
    )
    from tpu_cc_manager.drain import handshake
    from tpu_cc_manager.kubeclient.api import node_annotations
    from tpu_cc_manager.labels import CC_FAILED_REASON_LABEL

    rows = [
        f"{'NODE':<24} {'SLICE':<20} {'DESIRED':<10} {'STATE':<10} "
        f"{'READY':<6} NOTE"
    ]
    for node in api.list_nodes(args.selector):
        labels = node_labels(node)
        # Transient barrier markers / failure reason / remediation ladder:
        # the things an operator staring at a stuck rollout needs first.
        notes = []
        ladder = remediation_mod.describe_annotation(
            node_annotations(node).get(remediation_mod.REMEDIATION_ANNOTATION)
        )
        if ladder:
            notes.append(ladder)
        if labels.get(SLICE_STAGED_LABEL):
            notes.append(f"barrier:staged={labels[SLICE_STAGED_LABEL]}")
        if labels.get(SLICE_COMMIT_LABEL):
            notes.append(f"barrier:commit={labels[SLICE_COMMIT_LABEL]}")
        if labels.get(SLICE_FENCE_LABEL):
            notes.append(f"barrier:fence-gen={labels[SLICE_FENCE_LABEL]}")
        if labels.get(CC_FAILED_REASON_LABEL):
            notes.append(f"reason={labels[CC_FAILED_REASON_LABEL]}")
        token = handshake.request_token(
            labels.get(handshake.DRAIN_REQUESTED_LABEL)
        )
        if token is not None:
            subs = handshake.subscriber_labels_of(labels)
            # Same acceptance predicate as await_workload_acks: this
            # cycle's token OR the legacy bare ack (version-skewed job).
            accepted = (handshake.ack_value(token), handshake.ACKED)
            pending = sum(1 for v in subs.values() if v not in accepted)
            notes.append(
                f"drain:requested({len(subs) - pending}/{len(subs)} acked)"
            )
        rows.append(
            f"{node['metadata']['name']:<24} "
            f"{labels.get(SLICE_ID_LABEL, '-'):<20} "
            f"{labels.get(CC_MODE_LABEL, '-'):<10} "
            f"{labels.get(CC_MODE_STATE_LABEL, '-'):<10} "
            f"{labels.get(CC_READY_STATE_LABEL, '-'):<6} "
            f"{' '.join(notes) or '-'}"
        )
    print("\n".join(rows))
    return 0


def cmd_rbac_check(api, args) -> int:
    """Check every verb the agent uses (kubeclient/rest.py; the DaemonSet
    ClusterRole in deployments/manifests/daemonset.yaml must grant exactly
    these — including list nodes, which the slice barrier's peer discovery
    and the rolling orchestrator depend on)."""
    checks = [
        ("get", "nodes", None, True),
        ("list", "nodes", None, True),
        # `patch nodes` covers BOTH the metadata (labels/annotations)
        # writes and the quarantine taint write (spec.taints rides the
        # same resource + verb; ccmanager/remediation.py).
        ("patch", "nodes", None, True),
        ("watch", "nodes", None, True),
        ("list", "pods", args.namespace, True),
        # Events are best-effort (the agent degrades without them):
        # reported, but a denial doesn't fail the check. Node events live
        # in "default" (cluster-scoped involvedObject).
        ("create", "events", "default", False),
    ]
    ok = True
    for verb, resource, ns, required in checks:
        allowed = api.self_subject_access_review(verb, resource, namespace=ns)
        ok = ok and (allowed or not required)
        scope = f" (ns={ns})" if ns else ""
        verdict = "allowed" if allowed else (
            "DENIED" if required else "denied (optional)"
        )
        print(f"{verb:<6} {resource}{scope}: {verdict}")
    print("OK: RBAC sufficient" if ok else "FAIL: missing permissions")
    return 0 if ok else 1


def cmd_drain_subscribe(api, args) -> int:
    """Foreground sidecar process for the drain handshake: the pod's
    checkpoint command becomes the on_drain callback. SIGTERM/SIGINT
    unregister cleanly (pod shutdown must not leave a ghost subscriber
    the manager would wait on)."""
    import os
    import signal
    import subprocess

    from tpu_cc_manager.drain.handshake import DrainSubscriber

    node = args.node or os.environ.get("NODE_NAME")
    if not node:
        raise ValueError("--node or $NODE_NAME is required")

    current: dict = {"proc": None}

    def run_cmd(cmd: str) -> None:
        log.info("running: %s", cmd)
        proc = subprocess.Popen(cmd, shell=True)
        current["proc"] = proc
        try:
            rc = proc.wait()
        finally:
            current["proc"] = None
        if rc != 0:
            raise subprocess.CalledProcessError(rc, cmd)

    sub = DrainSubscriber(
        api, node, args.job,
        on_drain=lambda: run_cmd(args.on_drain),
        on_resume=(
            (lambda: run_cmd(args.on_resume)) if args.on_resume else None
        ),
        poll_interval_s=args.poll_interval,
    )
    args.subscriber = sub  # handle for callers/tests to stop() us

    def _shutdown(*_):
        # Also SIGTERM an in-flight checkpoint command: run() is blocked in
        # its wait, and the pod's grace period is ticking — if we merely set
        # the stop flag, kubelet SIGKILLs us before the unregister in
        # run()'s finally, leaving a ghost subscriber every future drain
        # would wait on.
        sub.stop(timeout_s=0)
        proc = current.get("proc")
        if proc is not None:
            proc.terminate()

    import threading

    if threading.current_thread() is threading.main_thread():
        # Signal handlers only exist on the main thread (tests drive this
        # command from a worker thread and stop via args.subscriber).
        for sig in (signal.SIGTERM, signal.SIGINT):
            signal.signal(sig, _shutdown)
    log.info(
        "drain subscriber %s watching node %s (ctrl-c / SIGTERM to leave)",
        sub.label, node,
    )
    sub.run()  # blocks; registers on entry, unregisters on the way out
    return 0


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    setup_logging(debug=args.debug)
    try:
        api = RestKube(ClusterConfig.load(args.kubeconfig))
    except Exception as e:  # noqa: BLE001 - any config failure is fatal here
        log.error("could not configure kubernetes client: %s", e)
        return 1
    from tpu_cc_manager.kubeclient.api import KubeApiError

    try:
        return {
            "rollout": cmd_rollout,
            "attest": cmd_attest,
            "status": cmd_status,
            "quarantine": cmd_quarantine,
            "unquarantine": cmd_unquarantine,
            "rbac-check": cmd_rbac_check,
            "drain-subscribe": cmd_drain_subscribe,
        }[args.command](api, args)
    except ValueError as e:
        log.error("usage error: %s", e)
        return 2
    except KubeApiError as e:
        log.error("apiserver error: %s", e)
        return 1


if __name__ == "__main__":
    sys.exit(main())
