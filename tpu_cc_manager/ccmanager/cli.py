"""CLI entry / bootstrap.

Reference analogue: main() (main.py:698-763; SURVEY.md §2 #1). Flags carry
the same env-var defaulting scheme (--kubeconfig/KUBECONFIG,
--default-cc-mode/DEFAULT_CC_MODE default "on", --node-name/NODE_NAME
required, --debug), plus TPU-specific additions: backend selection, smoke
workload selection, a Prometheus metrics port, and JSON logging.
"""

from __future__ import annotations

import argparse
import logging
import os
import signal
import sys
import threading
import time

from tpu_cc_manager.ccmanager.hostcaps import is_host_cc_enabled
from tpu_cc_manager.ccmanager.manager import CCManager
from tpu_cc_manager.ccmanager.metrics_server import start_metrics_server
from tpu_cc_manager.ccmanager.watchdog import start_from_env as start_watchdog
from tpu_cc_manager.kubeclient.rest import ClusterConfig, RestKube
from tpu_cc_manager.labels import MODE_OFF, VALID_MODES
from tpu_cc_manager.tpudev import load_backend
from tpu_cc_manager.utils.logging import setup_logging
from tpu_cc_manager.version import __version__

log = logging.getLogger(__name__)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpu-cc-manager",
        description="TPU confidential-computing node agent for GKE",
    )
    p.add_argument(
        "--kubeconfig",
        default=os.environ.get("KUBECONFIG"),
        help="kubeconfig path (default: in-cluster config, then $KUBECONFIG)",
    )
    p.add_argument(
        "-m", "--default-cc-mode",
        default=os.environ.get("DEFAULT_CC_MODE", "on"),
        help="mode applied when the desired-mode label is absent (default: on; "
        "forced to 'off' when the host lacks CC capability)",
    )
    p.add_argument(
        "--node-name",
        default=os.environ.get("NODE_NAME"),
        help="this node's name (default: $NODE_NAME; required)",
    )
    p.add_argument(
        "--tpu-backend",
        default=os.environ.get("TPU_CC_BACKEND", "tpuvm"),
        choices=("tpuvm", "fake"),
        help="device layer: 'tpuvm' on real TPU VMs, 'fake' for dry-runs",
    )
    p.add_argument(
        "--smoke-workload",
        default=os.environ.get("CC_SMOKE_WORKLOAD", "none"),
        help="JAX workload run as the final verify phase after each "
        "reconfigure (default: none; see tpu_cc_manager.smoke.runner.WORKLOADS)",
    )
    p.add_argument(
        "--metrics-port",
        type=int,
        default=int(os.environ.get("CC_METRICS_PORT", "0")),
        help="serve Prometheus metrics on this port (0 = disabled)",
    )
    p.add_argument("--json-logs", action="store_true",
                   default=os.environ.get("CC_JSON_LOGS", "").lower() in ("1", "true"))
    p.add_argument("-d", "--debug", action="store_true")
    p.add_argument("--version", action="version", version=__version__)
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    setup_logging(debug=args.debug, json_lines=args.json_logs)

    if not args.node_name:
        # Fatal misconfiguration (reference main.py:731-734).
        log.error("--node-name / NODE_NAME is required")
        return 1
    default_mode = args.default_cc_mode
    if default_mode not in VALID_MODES and default_mode not in ("ppcie",):
        log.error("invalid --default-cc-mode %r (valid: %s)", default_mode, VALID_MODES)
        return 1

    host_cc = is_host_cc_enabled()
    if not host_cc and default_mode != MODE_OFF:
        # Secure-by-default without bricking non-CC hosts
        # (reference main.py:736-742).
        log.warning(
            "host lacks CC capability; overriding default mode %r -> 'off'",
            default_mode,
        )
        default_mode = MODE_OFF

    try:
        api = RestKube(ClusterConfig.load(args.kubeconfig))
    except Exception as e:  # noqa: BLE001 - any config failure is fatal here
        log.error("could not configure kubernetes client: %s", e)
        return 1

    backend = load_backend(args.tpu_backend)
    # Node-local intent WAL (ccmanager/intent_journal.py) in the same
    # writable state dir the tpuvm backend persists its mode files to:
    # crash-restarts replay it BEFORE the first apiserver read, and a
    # total apiserver outage longer than CC_OFFLINE_GRACE_S flips the
    # agent into disconnected mode (serve last-known desired mode, defer
    # label writes as pending patches). CC_INTENT_JOURNAL=0 disables.
    intent_journal = None
    if os.environ.get("CC_INTENT_JOURNAL", "1").lower() not in (
        "0", "false", "no",
    ):
        from tpu_cc_manager.ccmanager.intent_journal import IntentJournal
        from tpu_cc_manager.tpudev.tpuvm import DEFAULT_STATE_DIR

        state_dir = (
            os.environ.get("CC_STATE_DIR")
            or getattr(backend, "state_dir", None)
            or DEFAULT_STATE_DIR
        )
        intent_journal = IntentJournal.from_state_dir(state_dir)
    manager = CCManager(
        api=api,
        backend=backend,
        node_name=args.node_name,
        default_mode=default_mode,
        host_cc_capable=host_cc,
        smoke_workload=args.smoke_workload,
        intent_journal=intent_journal,
    )
    # Failure containment (ccmanager/remediation.py): escalating ladder
    # from backoff retries through device re-reset and runtime restart to
    # quarantine (taint + label + fenced slice barrier), persisted in a
    # node annotation so it survives agent crash-restarts.
    from tpu_cc_manager.ccmanager import remediation as remediation_mod

    manager.remediation = remediation_mod.from_env(
        api,
        args.node_name,
        backend=backend,
        emit_event=manager._emit_node_event,
        metrics=manager.metrics,
        intents=intent_journal,
    )
    if args.metrics_port:
        # Same journal the manager records to, so /tracez and /statusz
        # serve the live reconcile traces; the intent journal backs the
        # /journalz endpoint `tpu-cc-ctl journal` reads.
        start_metrics_server(
            args.metrics_port, manager.metrics, journal=manager.journal,
            intent_journal=intent_journal,
        )
    # Graceful shutdown: SIGTERM (kubelet pod stop) sets the stop event so
    # the watch loop exits at the next event/timeout boundary and the
    # readiness file is withdrawn. A blocked watch read auto-retries after
    # the handler (PEP 475), so a hard-exit fallback thread guarantees the
    # process still dies promptly — but only while NO reconcile is in
    # flight: a half-applied hardware transition is never interrupted while
    # grace time (CC_SHUTDOWN_GRACE_S, default 20 s — size it below the
    # pod's terminationGracePeriod) remains. The preStop /bin/rm hook
    # covers the readiness file on the hard-exit path as well.
    stop = threading.Event()
    run_returned = threading.Event()
    grace_s = float(os.environ.get("CC_SHUTDOWN_GRACE_S", "20"))
    # Runtime-health watchdog (ccmanager/watchdog.py): probes the runtime
    # BETWEEN reconciles and demotes/restores cc.ready.state on sustained
    # degradation. Stands down while a reconcile is in flight.
    remediation = manager.remediation
    start_watchdog(
        api,
        backend,
        args.node_name,
        stop,
        is_busy=lambda: manager.reconciling,
        emit_event=manager._emit_node_event,
        metrics=manager.metrics,
        # Probe verdicts drive the quarantine probation window; the demote
        # edge fences this host's slice barrier so peers fail fast.
        on_probe=(remediation.note_probe if remediation is not None else None),
        on_condemn=(remediation.condemn if remediation is not None else None),
        # A demote (condemn) while the apiserver is dark is journaled as a
        # pending patch and flushed on reconnect; a write that LANDS while
        # stale deferred patches are queued supersedes them.
        defer_patch=manager.defer_patch_if_offline,
        note_patched=manager.note_direct_patch,
    )

    def _force_exit_when_idle():
        deadline = time.monotonic() + grace_s
        # Give a non-blocked loop the chance to exit cleanly; if run() has
        # already returned, the main thread owns shutdown — hard-exiting
        # here would race it and turn a clean stop into exit code 143.
        if run_returned.wait(2.0):
            return
        while manager.reconciling and time.monotonic() < deadline:
            if run_returned.wait(1.0):
                return
        # One final grace wait (not a bare is_set): if the reconcile just
        # finished, the main thread is milliseconds from returning — give
        # it that window so a clean stop doesn't report 143.
        if run_returned.wait(1.0):
            return
        manager.remove_readiness_file()
        os._exit(143)

    def _on_stop(*_):
        if stop.is_set():
            os._exit(143)  # second signal: immediate
        stop.set()
        t = threading.Thread(target=_force_exit_when_idle, daemon=True)
        t.start()

    try:
        signal.signal(signal.SIGTERM, _on_stop)
        signal.signal(signal.SIGINT, _on_stop)
    except ValueError:
        pass  # not the main thread (tests) — stop stays externally unset
    try:
        manager.run(stop)
    except Exception as e:  # noqa: BLE001 - crash-as-retry (reference main.py:757-759)
        log.error("manager terminated: %s", e, exc_info=True)
        return 1
    finally:
        run_returned.set()
    return 0


if __name__ == "__main__":
    sys.exit(main())
