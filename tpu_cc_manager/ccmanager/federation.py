"""Federated region-sharded rollouts: hierarchical lease fencing over
one global failure budget.

The single-process orchestrator (ccmanager/rolling.py) tops out at one
apiserver, one Lease, one process — ROADMAP item 1's standing ceiling.
This module composes the two primitives PR 4 and PR 15 already built —
the CAS-fenced rollout record and the stitched flight timeline — into a
two-level hierarchy that keeps the crash-anywhere / resume-exactly-once
guarantees when an entire *region* (orchestrator shard, apiserver, or
both) fails:

- **Regional shard**: one ordinary lease-fenced rollout per region
  (``RollingReconfigurator`` + ``RolloutLease``, unchanged semantics),
  against that region's own apiserver (or a region-label slice of one),
  checkpointing its regional slice of the plan into its regional lease.
  A shard SIGKILLed at any declared crash point resumes from its
  regional record exactly like today's ``--resume``.
- **Parent record**: ONE CAS document — the record annotation on a
  parent Lease object that nobody *holds* — carrying the global plan
  digest, the per-region status map, the single global failure budget /
  max-unavailable, the global ``budget_spend`` union, and a monotonic
  ``generation`` that fences force-aborted shards. Every shard
  read-modify-CAS-writes it at wave boundaries
  (:meth:`FederationGate.sync`); a 409 means another region wrote first,
  so the loser re-reads, re-merges and retries — budget spend is a
  node-name **set union**, so a CAS race between two shards charging the
  same window resolves to exactly-once by construction.

Fencing is hierarchical: a shard stops writing when (a) its regional
lease is lost (the existing ``FencedKube`` fence), (b) the parent
``generation`` has advanced past the one it attached at (a force-abort
bumped it — the wedged shard self-fences on its next sync), or (c) the
parent record is aborted. A regional apiserver blackout stalls only that
region's shard (its writes ride the shard's own retry ladder); the
parent's global spend keeps every other region's budget math honest in
the meantime.

Used by ``ctl rollout --regions``, ``hack/scale_bench.py --federation``
(SCALE_r03) and ``tests/test_federation.py``. Timeline stitching of the
per-region flight files stays in obs/flight.py (``stitch_files``) —
each shard writes its own JSONL shard, and
``ctl rollout-timeline --stitch`` reconstructs the one cross-region
exactly-once view.
"""

from __future__ import annotations

import hashlib
import json
import logging
from dataclasses import dataclass, field

from tpu_cc_manager.ccmanager import rollout_state
from tpu_cc_manager.kubeclient.api import KubeApi, KubeApiError
from tpu_cc_manager.labels import label_safe

log = logging.getLogger(__name__)

#: The parent record's Lease object (namespace = the rollout lease's).
#: Distinct from the regional rollout leases: nobody holds it, it is a
#: CAS document, and deleting it would reset the fencing generation.
PARENT_LEASE_NAME = "tpu-cc-rollout-parent"

#: Standard Kubernetes topology label used when regions are label slices
#: of one apiserver (``ctl rollout --regions r1,r2``).
REGION_LABEL = "topology.kubernetes.io/region"

#: Parent-document format version (independent of the regional
#: RolloutRecord's ``RECORD_VERSION`` — the parent is a new document,
#: not an evolution of the regional record).
PARENT_VERSION = 1

PARENT_IN_PROGRESS = rollout_state.RECORD_IN_PROGRESS
PARENT_COMPLETE = rollout_state.RECORD_COMPLETE
PARENT_HALTED = rollout_state.RECORD_HALTED
PARENT_ABORTED = "aborted"
#: A region registered at federation creation that has not synced yet.
#: Pre-seeding every region keeps ``all_complete`` honest (a parent is
#: complete only when EVERY declared region reports complete, not just
#: the ones that happened to sync) and gives every shard the true
#: region count at attach time.
PARENT_PENDING = "pending"

#: CAS retry ceiling for one parent write. Ten regions racing one wave
#: boundary serialize in at most N writes; the bound exists only to turn
#: a livelocked apiserver into an error instead of a hang.
_CAS_ATTEMPTS = 32


def regional_lease_name(region: str) -> str:
    """Per-region rollout lease name: regional shards must not contend
    on one Lease or the fence would serialize the federation."""
    return f"{rollout_state.LEASE_NAME}-{label_safe(region, max_len=40)}"


def regional_selector(selector: str, region: str) -> str:
    """The region slice of a pool selector when regions are label slices
    of one apiserver (the ctl ``--regions`` form)."""
    return f"{selector},{REGION_LABEL}={region}"


def plan_digest(mode: str, selector: str, regions: list[str]) -> str:
    """Digest of the federated plan identity. Shards attaching to the
    parent verify it so two operators racing different rollouts onto the
    same parent lease are refused instead of silently merged."""
    return hashlib.sha256(
        json.dumps(
            {"mode": mode, "selector": selector, "regions": sorted(regions)},
            sort_keys=True, separators=(",", ":"),
        ).encode()
    ).hexdigest()[:32]


@dataclass
class RegionSpec:
    """One region of a federated rollout: its name, its apiserver
    client, and its slice selector. ``lease_name`` defaults to the
    per-region rollout lease."""

    name: str
    api: KubeApi
    selector: str
    lease_name: str = ""

    def __post_init__(self) -> None:
        if not self.lease_name:
            self.lease_name = regional_lease_name(self.name)


@dataclass
class ParentRecord:
    """The one global document of a federated rollout (JSON in the
    parent Lease's record annotation). ``budget_spend`` is the global
    union of every region's charged node names; ``generation`` is the
    parent fencing token (bumped by force-abort so wedged shards
    self-fence); ``regions`` maps region name -> its last-synced
    status/progress."""

    mode: str
    selector: str
    digest: str
    max_unavailable: int
    failure_budget: int | None
    generation: int = 1
    budget_spend: list[str] = field(default_factory=list)
    regions: dict[str, dict] = field(default_factory=dict)
    status: str = PARENT_IN_PROGRESS
    halted_reason: str | None = None

    @classmethod
    def fresh(
        cls,
        mode: str,
        selector: str,
        regions: list[str],
        max_unavailable: int = 1,
        failure_budget: int | None = None,
    ) -> "ParentRecord":
        """A new federation's parent document with every region
        pre-registered as pending — the digest and the region count are
        fixed at creation, before any shard's first sync."""
        rec = cls(
            mode=mode, selector=selector,
            digest=plan_digest(mode, selector, list(regions)),
            max_unavailable=max_unavailable, failure_budget=failure_budget,
        )
        for region in regions:
            rec.regions[str(region)] = {
                "status": PARENT_PENDING, "done": 0, "total": 0,
                "generation": None,
            }
        return rec

    def charge_budget(self, nodes) -> None:
        self.budget_spend = sorted(set(self.budget_spend) | set(nodes))

    def note_region(
        self, region: str, status: str, done: int, total: int,
        generation: int | None = None,
    ) -> None:
        self.regions[region] = {
            "status": status,
            "done": int(done),
            "total": int(total),
            "generation": generation,
        }

    @property
    def all_complete(self) -> bool:
        return bool(self.regions) and all(
            r.get("status") == PARENT_COMPLETE for r in self.regions.values()
        )

    def to_json(self) -> str:
        return json.dumps(
            {
                "parentVersion": PARENT_VERSION,
                "mode": self.mode,
                "selector": self.selector,
                "digest": self.digest,
                "max_unavailable": self.max_unavailable,
                "failure_budget": self.failure_budget,
                "generation": self.generation,
                "budget_spend": list(self.budget_spend),
                "regions": self.regions,
                "status": self.status,
                "halted_reason": self.halted_reason,
            },
            sort_keys=True, separators=(",", ":"),
        )

    @classmethod
    def from_json(cls, data: str) -> "ParentRecord":
        try:
            obj = json.loads(data)
            version = int(obj.get("parentVersion") or 1)
            if version > PARENT_VERSION:
                raise rollout_state.RolloutFenced(
                    f"federated parent record v{version} is newer than this "
                    f"orchestrator understands (max v{PARENT_VERSION}); "
                    "upgrade, or abort the federation to discard"
                )
            return cls(
                mode=str(obj["mode"]),
                selector=str(obj["selector"]),
                digest=str(obj["digest"]),
                max_unavailable=int(obj.get("max_unavailable") or 1),
                failure_budget=(
                    int(obj["failure_budget"])
                    if obj.get("failure_budget") is not None else None
                ),
                generation=int(obj.get("generation") or 1),
                budget_spend=[str(n) for n in obj.get("budget_spend") or []],
                regions={
                    str(k): dict(v)
                    for k, v in (obj.get("regions") or {}).items()
                },
                status=str(obj.get("status") or PARENT_IN_PROGRESS),
                halted_reason=(
                    str(obj["halted_reason"])
                    if obj.get("halted_reason") else None
                ),
            )
        except rollout_state.RolloutFenced:
            raise
        except (ValueError, KeyError, TypeError) as e:
            raise rollout_state.RolloutFenced(
                f"unreadable federated parent record: {e}"
            ) from e


class ParentStore:
    """The parent record's CAS home: a Lease object nobody holds, on the
    designated parent apiserver. Chosen over a ConfigMap because every
    client in the repo (FakeKube, RestKube, the mock apiserver) already
    speaks honest resourceVersion CAS for Leases — the same primitive
    the regional fence rests on.

    Thread- and process-safe by construction: every mutation goes
    through :meth:`update`'s read-mutate-CAS-write loop, so concurrent
    shards serialize on the apiserver's resourceVersion, never on local
    locks."""

    def __init__(
        self,
        api: KubeApi,
        namespace: str | None = None,
        name: str = PARENT_LEASE_NAME,
    ) -> None:
        self.api = api
        self.namespace = namespace or rollout_state.lease_namespace()
        self.name = name

    def load(self) -> ParentRecord | None:
        """The current parent record, or None when no federation is in
        flight (no lease, or a lease with no record annotation)."""
        try:
            lease = self.api.get_lease(self.namespace, self.name)
        except KubeApiError as e:
            if e.status == 404:
                return None
            raise
        raw = ((lease.get("metadata") or {}).get("annotations") or {}).get(
            rollout_state.RECORD_ANNOTATION
        )
        return ParentRecord.from_json(raw) if raw else None

    def initialize(self, parent: ParentRecord, resume: bool) -> ParentRecord:
        """Create the parent document, or adopt the existing one.

        A fresh federation refuses an in-progress parent with a
        DIFFERENT plan digest (two operators racing different rollouts);
        a matching in-progress parent is adopted (another shard of the
        same federation got here first — the normal N-shard startup
        race). ``resume`` additionally demands an existing parent."""
        existing = self.load()
        if existing is not None:
            if existing.status in (PARENT_IN_PROGRESS, PARENT_HALTED):
                if existing.digest != parent.digest:
                    raise rollout_state.RolloutFenced(
                        "a different federated rollout is already in "
                        f"flight (digest {existing.digest} != "
                        f"{parent.digest}); abort it first"
                    )
                if existing.status == PARENT_HALTED and resume:
                    # A resumed federation brings a halted parent back to
                    # life exactly like a regional --resume restamps its
                    # record in-progress.
                    return self.update(self._revive)
                return existing
            if resume:
                raise rollout_state.RolloutFenced(
                    f"federated parent record is {existing.status}; "
                    "nothing to resume"
                )
        elif resume:
            raise rollout_state.RolloutFenced(
                "no federated parent record to resume"
            )
        return self._create(parent)

    @staticmethod
    def _revive(rec: ParentRecord) -> ParentRecord:
        rec.status = PARENT_IN_PROGRESS
        rec.halted_reason = None
        return rec

    def _create(self, parent: ParentRecord) -> ParentRecord:
        for _ in range(_CAS_ATTEMPTS):
            try:
                lease = self.api.get_lease(self.namespace, self.name)
            except KubeApiError as e:
                if e.status != 404:
                    raise
                try:
                    self.api.create_lease(
                        self.namespace, self.name, {"holderIdentity": ""}
                    )
                except KubeApiError as ce:
                    if ce.status != 409:
                        raise
                continue
            meta = lease.setdefault("metadata", {})
            annotations = meta.setdefault("annotations", {})
            prior = annotations.get(rollout_state.RECORD_ANNOTATION)
            if prior:
                # Someone wrote a record between load() and here: fall
                # back to adoption semantics via a fresh initialize.
                return self.initialize(parent, resume=False)
            annotations[rollout_state.RECORD_ANNOTATION] = parent.to_json()
            try:
                self.api.update_lease(self.namespace, self.name, lease)
                return parent
            except KubeApiError as e:
                if e.status != 409:
                    raise
        raise KubeApiError(
            None,
            f"parent lease {self.namespace}/{self.name}: create kept "
            "conflicting",
        )

    def update(self, mutate) -> ParentRecord:
        """Read-mutate-CAS-write the parent record. ``mutate`` receives
        the freshly read :class:`ParentRecord` and returns the record to
        persist (usually the same object, merged); it runs again on
        every 409, against the NEW read — set-union merges make the
        retried write idempotent, which is what turns a two-shard CAS
        race into an exactly-once budget charge. ``mutate`` may raise
        ``RolloutFenced`` to refuse (stale shard); that propagates."""
        last: KubeApiError | None = None
        for _ in range(_CAS_ATTEMPTS):
            lease = self.api.get_lease(self.namespace, self.name)
            raw = ((lease.get("metadata") or {}).get("annotations") or {}).get(
                rollout_state.RECORD_ANNOTATION
            )
            if not raw:
                raise rollout_state.RolloutFenced(
                    f"federated parent record vanished from "
                    f"{self.namespace}/{self.name} (aborted and discarded?)"
                )
            rec = mutate(ParentRecord.from_json(raw))
            lease.setdefault("metadata", {}).setdefault("annotations", {})[
                rollout_state.RECORD_ANNOTATION
            ] = rec.to_json()
            try:
                self.api.update_lease(self.namespace, self.name, lease)
                return rec
            except KubeApiError as e:
                if e.status != 409:
                    raise
                last = e
        raise KubeApiError(
            None,
            f"parent lease {self.namespace}/{self.name}: CAS kept "
            f"conflicting after {_CAS_ATTEMPTS} attempts ({last})",
        )

    def abort(self, reason: str = "operator-abort") -> ParentRecord:
        """Force-abort the federation: mark the parent aborted AND bump
        its generation. Every live shard's next sync sees a generation
        newer than the one it attached at and fences itself — the
        federated analogue of ``release_lease``'s self-fencing force
        release."""

        def _abort(rec: ParentRecord) -> ParentRecord:
            rec.status = PARENT_ABORTED
            rec.halted_reason = reason
            rec.generation += 1
            return rec

        return self.update(_abort)


class FederationGate:
    """One regional shard's handle on the parent record.

    Constructed per shard, attached once (capturing the parent
    generation as this shard's fence token), then passed to
    ``RollingReconfigurator(federation=...)`` which calls :meth:`sync`
    at every wave boundary inside the ``federation-boundary`` crash
    point. ``sync`` pushes this region's spend/status up, folds the
    global spend down, and raises ``RolloutFenced`` the moment the
    parent fences this shard out."""

    def __init__(
        self,
        store: ParentStore,
        region: str,
        metrics=None,
    ) -> None:
        self.store = store
        self.region = region
        self.metrics = metrics
        self.generation: int | None = None
        self.digest: str | None = None
        self.regions_total: int = 0

    def attach(self, parent: ParentRecord) -> None:
        """Adopt the parent's coordinates as this shard's fence token."""
        self.generation = parent.generation
        self.digest = parent.digest
        self.regions_total = max(len(parent.regions), 1)

    def to_record_dict(self) -> dict:
        """What the regional RolloutRecord persists (format v5) so a
        crash + ``--resume`` successor can reconnect to the parent."""
        return {
            "region": self.region,
            "regions": self.regions_total,
            "parent_namespace": self.store.namespace,
            "parent_name": self.store.name,
            "generation": self.generation,
            "digest": self.digest,
        }

    @classmethod
    def from_record_dict(
        cls, api: KubeApi, fed: dict, metrics=None
    ) -> "FederationGate":
        """Rebuild a shard's gate from its regional record's persisted
        ``federation`` field (the --resume path). The fence token is
        re-read from the LIVE parent — a resume is a new attachment, not
        a replay of the dead shard's token — but the digest must match:
        a parent that was aborted and recreated for a different plan
        must refuse the stale regional record."""
        store = ParentStore(
            api,
            namespace=str(fed.get("parent_namespace") or "") or None,
            name=str(fed.get("parent_name") or PARENT_LEASE_NAME),
        )
        gate = cls(store, region=str(fed["region"]), metrics=metrics)
        parent = store.load()
        if parent is None:
            raise rollout_state.RolloutFenced(
                "regional record is federated but the parent record is "
                "gone; abort the regional record to discard it"
            )
        if fed.get("digest") and parent.digest != fed["digest"]:
            raise rollout_state.RolloutFenced(
                "federated parent record belongs to a different rollout "
                f"(digest {parent.digest} != recorded {fed['digest']})"
            )
        if parent.status == PARENT_ABORTED:
            raise rollout_state.RolloutFenced(
                "federated rollout was aborted "
                f"({parent.halted_reason or 'no reason recorded'}); "
                "abort the regional record to discard it"
            )
        gate.attach(parent)
        return gate

    def _count(self, outcome: str) -> None:
        if self.metrics is not None:
            self.metrics.record_federation_sync(outcome)

    def sync(
        self,
        spend,
        status: str = PARENT_IN_PROGRESS,
        done: int = 0,
        total: int = 0,
        halted_reason: str | None = None,
        lease_generation: int | None = None,
    ) -> dict:
        """One wave-boundary exchange with the parent.

        Pushes this region's budget spend (union-merged — exactly-once
        under CAS races), status and progress; returns
        ``{"spend": [global union], "halted": bool, "reason": ...}``.
        Raises ``RolloutFenced`` when the parent generation has advanced
        past this shard's token (force-abort) or the parent is aborted —
        the wedged-shard self-fence."""
        if self.generation is None:
            raise rollout_state.RolloutFenced(
                "federation gate used before attach()"
            )
        regional_spend = sorted(set(spend))

        def _merge(rec: ParentRecord) -> ParentRecord:
            if rec.generation > self.generation:
                self._count("fenced")
                if self.metrics is not None:
                    self.metrics.record_federation_fence("parent-generation")
                raise rollout_state.RolloutFenced(
                    f"region {self.region}: parent generation "
                    f"{rec.generation} > attached {self.generation} "
                    "(force-aborted; this shard is fenced)"
                )
            if rec.status == PARENT_ABORTED:
                self._count("fenced")
                if self.metrics is not None:
                    self.metrics.record_federation_fence("parent-aborted")
                raise rollout_state.RolloutFenced(
                    f"region {self.region}: federated rollout aborted "
                    f"({rec.halted_reason or 'no reason recorded'})"
                )
            rec.charge_budget(regional_spend)
            rec.note_region(
                self.region, status, done, total,
                generation=lease_generation,
            )
            if status == PARENT_HALTED and rec.status == PARENT_IN_PROGRESS:
                rec.status = PARENT_HALTED
                rec.halted_reason = halted_reason or (
                    f"region {self.region} halted"
                )
            elif rec.all_complete and rec.status == PARENT_IN_PROGRESS:
                rec.status = PARENT_COMPLETE
            return rec

        parent = self.store.update(_merge)
        self._count("ok")
        if self.metrics is not None:
            self.metrics.set_federation_budget_spent(
                len(parent.budget_spend)
            )
        halted = parent.status == PARENT_HALTED and status != PARENT_HALTED
        return {
            "spend": list(parent.budget_spend),
            "halted": halted,
            "reason": parent.halted_reason if halted else None,
            "parent_status": parent.status,
        }


def describe_parent(parent: ParentRecord | None) -> str:
    """One operator-readable block for ``tpu-cc-ctl status`` /
    ``rollout --regions`` output."""
    if parent is None:
        return "federation: no parent record"
    lines = [
        f"federation: mode={parent.mode} status={parent.status} "
        f"gen={parent.generation} digest={parent.digest} "
        f"budget_spend={len(parent.budget_spend)}"
        + (f"/{parent.failure_budget}" if parent.failure_budget is not None
           else "")
    ]
    for name in sorted(parent.regions):
        r = parent.regions[name]
        lines.append(
            f"  region {name}: {r.get('status')} "
            f"{r.get('done')}/{r.get('total')} group(s)"
            + (f" gen={r.get('generation')}" if r.get("generation") else "")
        )
    if parent.halted_reason:
        lines.append(f"  halted: {parent.halted_reason}")
    return "\n".join(lines)
