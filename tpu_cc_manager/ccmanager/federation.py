"""Federated region-sharded rollouts: hierarchical lease fencing over
one global failure budget.

The single-process orchestrator (ccmanager/rolling.py) tops out at one
apiserver, one Lease, one process — ROADMAP item 1's standing ceiling.
This module composes the two primitives PR 4 and PR 15 already built —
the CAS-fenced rollout record and the stitched flight timeline — into a
two-level hierarchy that keeps the crash-anywhere / resume-exactly-once
guarantees when an entire *region* (orchestrator shard, apiserver, or
both) fails:

- **Regional shard**: one ordinary lease-fenced rollout per region
  (``RollingReconfigurator`` + ``RolloutLease``, unchanged semantics),
  against that region's own apiserver (or a region-label slice of one),
  checkpointing its regional slice of the plan into its regional lease.
  A shard SIGKILLed at any declared crash point resumes from its
  regional record exactly like today's ``--resume``.
- **Parent record**: ONE CAS document — the record annotation on a
  parent Lease object that nobody *holds* — carrying the global plan
  digest, the per-region status map, the single global failure budget /
  max-unavailable, the global ``budget_spend`` union, and a monotonic
  ``generation`` that fences force-aborted shards. Every shard
  read-modify-CAS-writes it at wave boundaries
  (:meth:`FederationGate.sync`); a 409 means another region wrote first,
  so the loser re-reads, re-merges and retries — budget spend is a
  node-name **set union**, so a CAS race between two shards charging the
  same window resolves to exactly-once by construction.

Fencing is hierarchical: a shard stops writing when (a) its regional
lease is lost (the existing ``FencedKube`` fence), (b) the parent
``generation`` has advanced past the one it attached at (a force-abort
bumped it — the wedged shard self-fences on its next sync), or (c) the
parent record is aborted. A regional apiserver blackout stalls only that
region's shard (its writes ride the shard's own retry ladder); the
parent's global spend keeps every other region's budget math honest in
the meantime.

Used by ``ctl rollout --regions``, ``hack/scale_bench.py --federation``
(SCALE_r03) and ``tests/test_federation.py``. Timeline stitching of the
per-region flight files stays in obs/flight.py (``stitch_files``) —
each shard writes its own JSONL shard, and
``ctl rollout-timeline --stitch`` reconstructs the one cross-region
exactly-once view.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import time
from dataclasses import dataclass, field

from tpu_cc_manager.ccmanager import rollout_state
from tpu_cc_manager.ccmanager.intent_journal import (
    OfflineTracker,
    is_outage_error,
)
from tpu_cc_manager.kubeclient.api import (
    KubeApi,
    KubeApiError,
    caller_retry_attempts,
    classify_kube_error,
)
from tpu_cc_manager.labels import label_safe
from tpu_cc_manager.utils import retry as retry_mod

log = logging.getLogger(__name__)

#: The parent record's Lease object (namespace = the rollout lease's).
#: Distinct from the regional rollout leases: nobody holds it, it is a
#: CAS document, and deleting it would reset the fencing generation.
PARENT_LEASE_NAME = "tpu-cc-rollout-parent"

#: Standard Kubernetes topology label used when regions are label slices
#: of one apiserver (``ctl rollout --regions r1,r2``).
REGION_LABEL = "topology.kubernetes.io/region"

#: Parent-document format version (independent of the regional
#: RolloutRecord's ``RECORD_VERSION`` — the parent is a new document,
#: not an evolution of the regional record). History:
#: 1 (PR 16): plan digest, per-region status map, one global budget,
#: ``budget_spend`` union, fencing ``generation``.
#: 2: adds ``escrow`` (per-region slices of the global budget reserved
#: for autonomous degraded-mode spending during a parent-plane
#: blackout), ``region_budgets`` and ``region_max_unavailable``
#: (heterogeneous per-region limits). Written ONLY when one of those
#: maps is populated, so budgetless/homogeneous federations keep
#: round-tripping through v1 binaries; an escrow-bearing parent resumed
#: by an escrow-unaware binary would silently drop the ledger and let
#: dark regions overspend, so v2 is refused loudly by older parsers.
PARENT_VERSION = 2
#: What parents WITHOUT the escrow/heterogeneous fields write.
PARENT_VERSION_NO_ESCROW = 1

PARENT_IN_PROGRESS = rollout_state.RECORD_IN_PROGRESS
PARENT_COMPLETE = rollout_state.RECORD_COMPLETE
PARENT_HALTED = rollout_state.RECORD_HALTED
PARENT_ABORTED = "aborted"
#: What :meth:`FederationGate.sync` reports as ``parent_status`` while
#: the parent apiserver is unreachable (transport-level failures only —
#: an apiserver that ANSWERS an error is not an outage).
PARENT_OFFLINE = "offline"

#: Degraded-mode halt reasons. ``escrow-exhausted`` is regional-only:
#: a dark shard that spent its escrowed slice stops itself without
#: halting the (unreachable) parent; siblings keep rolling. So is
#: ``region-failure-budget-exceeded`` (a heterogeneous per-region cap) —
#: only a GLOBAL budget breach halts the whole federation.
ESCROW_EXHAUSTED_REASON = "escrow-exhausted"
REGION_BUDGET_REASON = "region-failure-budget-exceeded"
_REGIONAL_ONLY_HALTS = (ESCROW_EXHAUSTED_REASON, REGION_BUDGET_REASON)

#: How long the parent plane must be dark (transport errors on every
#: sync) before a shard declares DEGRADED mode and journals the
#: parent-offline flight event. The escrow safety math applies from the
#: very first failed sync regardless — the grace only debounces the
#: operator-facing state flip, mirroring CC_OFFLINE_GRACE_S one level
#: down the hierarchy.
FEDERATION_OFFLINE_GRACE_ENV = "CC_FEDERATION_OFFLINE_GRACE_S"
DEFAULT_FEDERATION_OFFLINE_GRACE_S = 60.0


def federation_offline_grace_s() -> float:
    raw = os.environ.get(FEDERATION_OFFLINE_GRACE_ENV)
    if raw is None:
        return DEFAULT_FEDERATION_OFFLINE_GRACE_S
    try:
        return float(raw)
    except ValueError:
        log.warning(
            "%s=%r is not a number; using %.0f",
            FEDERATION_OFFLINE_GRACE_ENV, raw,
            DEFAULT_FEDERATION_OFFLINE_GRACE_S,
        )
        return DEFAULT_FEDERATION_OFFLINE_GRACE_S


class ParentUnreadable(rollout_state.RolloutFenced):
    """The parent record exists but cannot be parsed. Distinct from the
    version refusal so ``abort`` (the documented recovery) can discard a
    corrupt parent instead of tracebacking on it."""


#: A region registered at federation creation that has not synced yet.
#: Pre-seeding every region keeps ``all_complete`` honest (a parent is
#: complete only when EVERY declared region reports complete, not just
#: the ones that happened to sync) and gives every shard the true
#: region count at attach time.
PARENT_PENDING = "pending"

#: CAS retry ceiling for one parent write. Ten regions racing one wave
#: boundary serialize in at most N writes; the bound exists only to turn
#: a livelocked apiserver into an error instead of a hang.
_CAS_ATTEMPTS = 32


def regional_lease_name(region: str) -> str:
    """Per-region rollout lease name: regional shards must not contend
    on one Lease or the fence would serialize the federation."""
    return f"{rollout_state.LEASE_NAME}-{label_safe(region, max_len=40)}"


def regional_selector(selector: str, region: str) -> str:
    """The region slice of a pool selector when regions are label slices
    of one apiserver (the ctl ``--regions`` form)."""
    return f"{selector},{REGION_LABEL}={region}"


def plan_digest(mode: str, selector: str, regions: list[str]) -> str:
    """Digest of the federated plan identity. Shards attaching to the
    parent verify it so two operators racing different rollouts onto the
    same parent lease are refused instead of silently merged."""
    return hashlib.sha256(
        json.dumps(
            {"mode": mode, "selector": selector, "regions": sorted(regions)},
            sort_keys=True, separators=(",", ":"),
        ).encode()
    ).hexdigest()[:32]


@dataclass
class RegionSpec:
    """One region of a federated rollout: its name, its apiserver
    client, and its slice selector. ``lease_name`` defaults to the
    per-region rollout lease."""

    name: str
    api: KubeApi
    selector: str
    lease_name: str = ""

    def __post_init__(self) -> None:
        if not self.lease_name:
            self.lease_name = regional_lease_name(self.name)


@dataclass
class ParentRecord:
    """The one global document of a federated rollout (JSON in the
    parent Lease's record annotation). ``budget_spend`` is the global
    union of every region's charged node names; ``generation`` is the
    parent fencing token (bumped by force-abort so wedged shards
    self-fence); ``regions`` maps region name -> its last-synced
    status/progress."""

    mode: str
    selector: str
    digest: str
    max_unavailable: int
    failure_budget: int | None
    generation: int = 1
    budget_spend: list[str] = field(default_factory=list)
    regions: dict[str, dict] = field(default_factory=dict)
    status: str = PARENT_IN_PROGRESS
    halted_reason: str | None = None
    # Budget escrow (format v2): per-region slices of the global budget
    # reserved for degraded-mode spending while the parent plane is
    # dark. Invariant: len(budget_spend) + sum(escrow.values()) <=
    # failure_budget — a dark region charging only against its slice can
    # never push the federation over the global budget.
    escrow: dict[str, int] = field(default_factory=dict)
    # Heterogeneous per-region limits (format v2): a region absent from
    # either map falls back to the global value.
    region_budgets: dict[str, int] = field(default_factory=dict)
    region_max_unavailable: dict[str, int] = field(default_factory=dict)

    @classmethod
    def fresh(
        cls,
        mode: str,
        selector: str,
        regions: list[str],
        max_unavailable: int = 1,
        failure_budget: int | None = None,
        region_budgets: dict[str, int] | None = None,
        region_max_unavailable: dict[str, int] | None = None,
    ) -> "ParentRecord":
        """A new federation's parent document with every region
        pre-registered as pending — the digest and the region count are
        fixed at creation, before any shard's first sync."""
        rec = cls(
            mode=mode, selector=selector,
            digest=plan_digest(mode, selector, list(regions)),
            max_unavailable=max_unavailable, failure_budget=failure_budget,
            region_budgets=dict(region_budgets or {}),
            region_max_unavailable=dict(region_max_unavailable or {}),
        )
        for region in regions:
            rec.regions[str(region)] = {
                "status": PARENT_PENDING, "done": 0, "total": 0,
                "generation": None,
            }
        return rec

    def charge_budget(self, nodes) -> None:
        self.budget_spend = sorted(set(self.budget_spend) | set(nodes))

    def note_region(
        self, region: str, status: str, done: int, total: int,
        generation: int | None = None,
        charged: list[str] | None = None,
        synced_at: float | None = None,
    ) -> None:
        entry = {
            "status": status,
            "done": int(done),
            "total": int(total),
            "generation": generation,
        }
        if charged is not None:
            # Per-region spend attribution: the subset of budget_spend
            # this region itself charged (set-union, exactly-once under
            # CAS races like the global ledger). Only maintained when a
            # budget exists — it is what heterogeneous caps and escrow
            # re-reservation are computed from.
            entry["charged"] = sorted(set(charged))
        if synced_at is not None:
            # Display-only wall stamp for `ctl status` last-sync age;
            # NEVER consulted by fencing (fencing is wall-clock-free:
            # generation tokens and monotonic local clocks only).
            entry["synced_at"] = round(float(synced_at), 3)
        self.regions[region] = entry

    @property
    def all_complete(self) -> bool:
        return bool(self.regions) and all(
            r.get("status") == PARENT_COMPLETE for r in self.regions.values()
        )

    def region_charged(self, region: str) -> set[str]:
        """The spend this region itself charged (its slice of the global
        union), per the persisted per-region attribution."""
        return set((self.regions.get(region) or {}).get("charged") or [])

    def to_json(self) -> str:
        # Serialize at the LOWEST version that expresses the populated
        # fields (the regional record's downgrade-compat discipline): a
        # budgetless/homogeneous federation stays v1 so older binaries
        # keep adopting it; any escrow or per-region limit forces v2 and
        # a loud refusal from escrow-unaware parsers.
        versioned = bool(
            self.escrow or self.region_budgets or self.region_max_unavailable
        )
        body = {
            "parentVersion": (
                PARENT_VERSION if versioned else PARENT_VERSION_NO_ESCROW
            ),
            "mode": self.mode,
            "selector": self.selector,
            "digest": self.digest,
            "max_unavailable": self.max_unavailable,
            "failure_budget": self.failure_budget,
            "generation": self.generation,
            "budget_spend": list(self.budget_spend),
            "regions": self.regions,
            "status": self.status,
            "halted_reason": self.halted_reason,
        }
        if versioned:
            body["escrow"] = {k: int(v) for k, v in self.escrow.items()}
            if self.region_budgets:
                body["region_budgets"] = {
                    k: int(v) for k, v in self.region_budgets.items()
                }
            if self.region_max_unavailable:
                body["region_max_unavailable"] = {
                    k: int(v) for k, v in self.region_max_unavailable.items()
                }
        return json.dumps(body, sort_keys=True, separators=(",", ":"))

    @classmethod
    def from_json(cls, data: str) -> "ParentRecord":
        try:
            obj = json.loads(data)
            version = int(obj.get("parentVersion") or 1)
            if version > PARENT_VERSION:
                raise rollout_state.RolloutFenced(
                    f"federated parent record v{version} is newer than this "
                    f"orchestrator understands (max v{PARENT_VERSION}); "
                    "upgrade, or abort the federation to discard"
                )
            return cls(
                mode=str(obj["mode"]),
                selector=str(obj["selector"]),
                digest=str(obj["digest"]),
                max_unavailable=int(obj.get("max_unavailable") or 1),
                failure_budget=(
                    int(obj["failure_budget"])
                    if obj.get("failure_budget") is not None else None
                ),
                generation=int(obj.get("generation") or 1),
                budget_spend=[str(n) for n in obj.get("budget_spend") or []],
                regions={
                    str(k): dict(v)
                    for k, v in (obj.get("regions") or {}).items()
                },
                status=str(obj.get("status") or PARENT_IN_PROGRESS),
                halted_reason=(
                    str(obj["halted_reason"])
                    if obj.get("halted_reason") else None
                ),
                escrow={
                    str(k): int(v)
                    for k, v in (obj.get("escrow") or {}).items()
                },
                region_budgets={
                    str(k): int(v)
                    for k, v in (obj.get("region_budgets") or {}).items()
                },
                region_max_unavailable={
                    str(k): int(v)
                    for k, v in (obj.get("region_max_unavailable") or {}).items()
                },
            )
        except rollout_state.RolloutFenced:
            raise
        except (ValueError, KeyError, TypeError) as e:
            raise ParentUnreadable(
                f"unreadable federated parent record: {e}"
            ) from e


class ParentStore:
    """The parent record's CAS home: a Lease object nobody holds, on the
    designated parent apiserver. Chosen over a ConfigMap because every
    client in the repo (FakeKube, RestKube, the mock apiserver) already
    speaks honest resourceVersion CAS for Leases — the same primitive
    the regional fence rests on.

    Thread- and process-safe by construction: every mutation goes
    through :meth:`update`'s read-mutate-CAS-write loop, so concurrent
    shards serialize on the apiserver's resourceVersion, never on local
    locks."""

    def __init__(
        self,
        api: KubeApi,
        namespace: str | None = None,
        name: str = PARENT_LEASE_NAME,
        retry_policy: retry_mod.RetryPolicy | None = None,
    ) -> None:
        self.api = api
        self.namespace = namespace or rollout_state.lease_namespace()
        self.name = name
        # Every parent read/write rides the shared retry ladder like any
        # other client path: Retry-After honored, transients re-tried,
        # and attempts collapsed to 1 when the client retries internally
        # (RestKube) so ladders never nest. A 409 is classified
        # non-transient, so CAS conflicts still surface to the
        # read-mutate-write loops below instead of being blindly
        # replayed against a stale resourceVersion.
        self.retry = retry_policy or retry_mod.RetryPolicy(
            max_attempts=caller_retry_attempts(self.api),
            base_delay_s=0.2, max_delay_s=2.0,
        )

    def _get_lease(self) -> dict:
        return self.retry.call(
            lambda: self.api.get_lease(self.namespace, self.name),
            op="federation.parent-get", classify=classify_kube_error,
        )

    def _put_lease(self, lease: dict) -> dict:
        return self.retry.call(
            lambda: self.api.update_lease(self.namespace, self.name, lease),
            op="federation.parent-cas", classify=classify_kube_error,
        )

    def load(self) -> ParentRecord | None:
        """The current parent record, or None when no federation is in
        flight (no lease, or a lease with no record annotation)."""
        try:
            lease = self._get_lease()
        except KubeApiError as e:
            if e.status == 404:
                return None
            raise
        raw = ((lease.get("metadata") or {}).get("annotations") or {}).get(
            rollout_state.RECORD_ANNOTATION
        )
        return ParentRecord.from_json(raw) if raw else None

    def initialize(self, parent: ParentRecord, resume: bool) -> ParentRecord:
        """Create the parent document, or adopt the existing one.

        A fresh federation refuses an in-progress parent with a
        DIFFERENT plan digest (two operators racing different rollouts);
        a matching in-progress parent is adopted (another shard of the
        same federation got here first — the normal N-shard startup
        race). ``resume`` additionally demands an existing parent."""
        existing = self.load()
        if existing is not None:
            if existing.status in (PARENT_IN_PROGRESS, PARENT_HALTED):
                if existing.digest != parent.digest:
                    raise rollout_state.RolloutFenced(
                        "a different federated rollout is already in "
                        f"flight (digest {existing.digest} != "
                        f"{parent.digest}); abort it first"
                    )
                if existing.status == PARENT_HALTED and resume:
                    # A resumed federation brings a halted parent back to
                    # life exactly like a regional --resume restamps its
                    # record in-progress.
                    return self.update(self._revive)
                return existing
            if resume:
                raise rollout_state.RolloutFenced(
                    f"federated parent record is {existing.status}; "
                    "nothing to resume"
                )
        elif resume:
            raise rollout_state.RolloutFenced(
                "no federated parent record to resume"
            )
        return self._create(parent)

    @staticmethod
    def _revive(rec: ParentRecord) -> ParentRecord:
        rec.status = PARENT_IN_PROGRESS
        rec.halted_reason = None
        return rec

    def _create(self, parent: ParentRecord) -> ParentRecord:
        for _ in range(_CAS_ATTEMPTS):
            try:
                lease = self._get_lease()
            except KubeApiError as e:
                if e.status != 404:
                    raise
                try:
                    self.retry.call(
                        lambda: self.api.create_lease(
                            self.namespace, self.name, {"holderIdentity": ""}
                        ),
                        op="federation.parent-create",
                        classify=classify_kube_error,
                    )
                except KubeApiError as ce:
                    if ce.status != 409:
                        raise
                continue
            meta = lease.setdefault("metadata", {})
            annotations = meta.setdefault("annotations", {})
            prior = annotations.get(rollout_state.RECORD_ANNOTATION)
            if prior:
                # Someone wrote a record between load() and here: fall
                # back to adoption semantics via a fresh initialize.
                return self.initialize(parent, resume=False)
            annotations[rollout_state.RECORD_ANNOTATION] = parent.to_json()
            try:
                self._put_lease(lease)
                return parent
            except KubeApiError as e:
                if e.status != 409:
                    raise
        raise KubeApiError(
            None,
            f"parent lease {self.namespace}/{self.name}: create kept "
            "conflicting",
        )

    def update(self, mutate) -> ParentRecord:
        """Read-mutate-CAS-write the parent record. ``mutate`` receives
        the freshly read :class:`ParentRecord` and returns the record to
        persist (usually the same object, merged); it runs again on
        every 409, against the NEW read — set-union merges make the
        retried write idempotent, which is what turns a two-shard CAS
        race into an exactly-once budget charge. ``mutate`` may raise
        ``RolloutFenced`` to refuse (stale shard); that propagates."""
        last: KubeApiError | None = None
        for _ in range(_CAS_ATTEMPTS):
            lease = self._get_lease()
            raw = ((lease.get("metadata") or {}).get("annotations") or {}).get(
                rollout_state.RECORD_ANNOTATION
            )
            if not raw:
                raise rollout_state.RolloutFenced(
                    f"federated parent record vanished from "
                    f"{self.namespace}/{self.name} (aborted and discarded?)"
                )
            rec = mutate(ParentRecord.from_json(raw))
            lease.setdefault("metadata", {}).setdefault("annotations", {})[
                rollout_state.RECORD_ANNOTATION
            ] = rec.to_json()
            try:
                self._put_lease(lease)
                return rec
            except KubeApiError as e:
                if e.status != 409:
                    raise
                last = e
        raise KubeApiError(
            None,
            f"parent lease {self.namespace}/{self.name}: CAS kept "
            f"conflicting after {_CAS_ATTEMPTS} attempts ({last})",
        )

    def abort(self, reason: str = "operator-abort") -> ParentRecord:
        """Force-abort the federation: mark the parent aborted AND bump
        its generation. Every live shard's next sync sees a generation
        newer than the one it attached at and fences itself — the
        federated analogue of ``release_lease``'s self-fencing force
        release. A CORRUPT parent (unparseable annotation) is replaced
        by a synthetic aborted tombstone instead of tracebacking:
        ``abort`` is the documented recovery for exactly that state, and
        any shard still attached fences on the tombstone's aborted
        status at its next sync."""

        def _abort(rec: ParentRecord) -> ParentRecord:
            rec.status = PARENT_ABORTED
            rec.halted_reason = reason
            rec.generation += 1
            return rec

        try:
            return self.update(_abort)
        except ParentUnreadable as e:
            log.warning(
                "parent record %s/%s is unreadable (%s); replacing with "
                "an aborted tombstone", self.namespace, self.name, e,
            )
            return self._entomb(reason)

    def _entomb(self, reason: str) -> ParentRecord:
        """CAS-overwrite an unparseable parent annotation with a minimal
        aborted record. The aborted STATUS (checked before anything else
        a shard could trust from a corrupt document) is the operative
        fence here, not the generation."""
        tomb = ParentRecord(
            mode="?", selector="?", digest="discarded-corrupt",
            max_unavailable=1, failure_budget=None,
            status=PARENT_ABORTED,
            halted_reason=f"{reason} (previous record unreadable)",
        )
        for _ in range(_CAS_ATTEMPTS):
            lease = self._get_lease()
            lease.setdefault("metadata", {}).setdefault("annotations", {})[
                rollout_state.RECORD_ANNOTATION
            ] = tomb.to_json()
            try:
                self._put_lease(lease)
                return tomb
            except KubeApiError as e:
                if e.status != 409:
                    raise
        raise KubeApiError(
            None,
            f"parent lease {self.namespace}/{self.name}: tombstone write "
            "kept conflicting",
        )


class FederationGate:
    """One regional shard's handle on the parent record.

    Constructed per shard, attached once (capturing the parent
    generation as this shard's fence token), then passed to
    ``RollingReconfigurator(federation=...)`` which calls :meth:`sync`
    at every wave boundary inside the ``federation-boundary`` crash
    point. ``sync`` pushes this region's spend/status up, folds the
    global spend down, and raises ``RolloutFenced`` the moment the
    parent fences this shard out."""

    def __init__(
        self,
        store: ParentStore,
        region: str,
        metrics=None,
        offline_grace_s: float | None = None,
        clock=time.monotonic,
        wall=time.time,
    ) -> None:
        self.store = store
        self.region = region
        self.metrics = metrics
        self.generation: int | None = None
        self.digest: str | None = None
        self.regions_total: int = 0
        #: Heterogeneous per-region cap (None = global budget only).
        self.region_budget: int | None = None
        #: This shard's escrowed slice of the global budget — what it may
        #: charge autonomously while the parent plane is dark. None when
        #: the federation has no budget at all (nothing to escrow).
        self.escrow_balance: int | None = None
        #: The global spend union at the last SUCCESSFUL sync: anything
        #: in the local record beyond this is dark spend still pending
        #: reconciliation, charged against the escrow balance.
        self.acked_spend: set[str] = set()
        #: Cumulative spend this region itself charged (mirrors the
        #: parent's per-region attribution).
        self.charged: set[str] = set()
        self.wall = wall
        self.offline = OfflineTracker(
            grace_s=(
                offline_grace_s if offline_grace_s is not None
                else federation_offline_grace_s()
            ),
            clock=clock,
        )
        self._was_engaged = False

    def attach(self, parent: ParentRecord) -> None:
        """Adopt the parent's coordinates as this shard's fence token,
        and CAS-reserve this region's attach-time escrow slice. A parent
        plane already dark at attach leaves a provisional slice computed
        from the last-seen snapshot (still bounded by the invariant —
        the reservation lands on the first successful sync)."""
        self.generation = parent.generation
        self.digest = parent.digest
        self.regions_total = max(len(parent.regions), 1)
        self.region_budget = parent.region_budgets.get(self.region)
        self.acked_spend = set(parent.budget_spend)
        self.charged = parent.region_charged(self.region)
        if parent.failure_budget is None:
            self.escrow_balance = None
            return
        try:
            live = self.store.update(self._reserve_only)
        except KubeApiError as e:
            if not is_outage_error(e):
                raise
            self.offline.note_failure()
            self.escrow_balance = self._escrow_target(
                parent, self.charged, terminal=False
            )
            log.warning(
                "region %s: parent plane dark at attach; provisional "
                "escrow slice %s", self.region, self.escrow_balance,
            )
            return
        self.offline.note_success()
        self.escrow_balance = live.escrow.get(self.region, 0)
        self.acked_spend = set(live.budget_spend)
        self.charged = live.region_charged(self.region)

    def _reserve_only(self, rec: ParentRecord) -> ParentRecord:
        """Mutator for the attach-time reservation: fence checks plus
        the escrow slice, no status/progress merge."""
        self._guard(rec)
        target = self._escrow_target(
            rec, rec.region_charged(self.region) | self.charged,
            terminal=False,
        )
        if target is not None:
            rec.escrow[self.region] = target
        return rec

    def _escrow_target(
        self, rec: ParentRecord, charged: set[str], terminal: bool
    ) -> int | None:
        """How much of the global budget this region should hold in
        escrow right now. None when there is no budget (nothing to
        bound); 0 for terminal regions (unused escrow returned). The
        slice is the region's remaining heterogeneous allowance when one
        is set, else a fair ceil-share of the remaining global budget —
        always capped so len(budget_spend) + sum(escrow) never exceeds
        failure_budget."""
        if rec.failure_budget is None:
            return None
        if terminal:
            return 0
        others = sum(
            v for r, v in rec.escrow.items() if r != self.region
        )
        spend = len(rec.budget_spend)
        free = max(0, rec.failure_budget - spend - others)
        rb = rec.region_budgets.get(self.region)
        if rb is not None:
            want = max(0, rb - len(charged))
        else:
            remaining = max(0, rec.failure_budget - spend)
            want = -(-remaining // max(len(rec.regions) or 1, 1))
        return min(want, free)

    def _guard(self, rec: ParentRecord) -> None:
        """The hierarchical fence checks every parent write runs behind:
        generation advance (force-abort), aborted status, and plan
        digest (an abort-and-recreate during a blackout must fence the
        stale shard even if the new plan reset the generation)."""
        if rec.generation > self.generation:
            self._count("fenced")
            if self.metrics is not None:
                self.metrics.record_federation_fence("parent-generation")
            raise rollout_state.RolloutFenced(
                f"region {self.region}: parent generation "
                f"{rec.generation} > attached {self.generation} "
                "(force-aborted; this shard is fenced)"
            )
        if rec.status == PARENT_ABORTED:
            self._count("fenced")
            if self.metrics is not None:
                self.metrics.record_federation_fence("parent-aborted")
            raise rollout_state.RolloutFenced(
                f"region {self.region}: federated rollout aborted "
                f"({rec.halted_reason or 'no reason recorded'})"
            )
        if self.digest and rec.digest != self.digest:
            self._count("fenced")
            if self.metrics is not None:
                self.metrics.record_federation_fence("parent-digest")
            raise rollout_state.RolloutFenced(
                f"region {self.region}: parent record belongs to a "
                f"different rollout (digest {rec.digest} != attached "
                f"{self.digest})"
            )

    def to_record_dict(self) -> dict:
        """What the regional RolloutRecord persists so a crash +
        ``--resume`` successor can reconnect to the parent. With a
        budget in play this carries the escrow ledger (balance, acked
        spend, attribution — format v6): a successor resuming WHILE the
        parent is still dark must know exactly how much it may keep
        charging."""
        d = {
            "region": self.region,
            "regions": self.regions_total,
            "parent_namespace": self.store.namespace,
            "parent_name": self.store.name,
            "generation": self.generation,
            "digest": self.digest,
        }
        if self.escrow_balance is not None:
            d["escrow"] = int(self.escrow_balance)
            d["acked_spend"] = sorted(self.acked_spend)
            d["charged"] = sorted(self.charged)
            if self.region_budget is not None:
                d["region_budget"] = int(self.region_budget)
        return d

    @classmethod
    def from_record_dict(
        cls, api: KubeApi, fed: dict, metrics=None,
        offline_grace_s: float | None = None, clock=time.monotonic,
    ) -> "FederationGate":
        """Rebuild a shard's gate from its regional record's persisted
        ``federation`` field (the --resume path). The fence token is
        re-read from the LIVE parent — a resume is a new attachment, not
        a replay of the dead shard's token — but the digest must match:
        a parent that was aborted and recreated for a different plan
        must refuse the stale regional record.

        When the parent plane is DARK (transport error) and the record
        carries the escrow ledger, the gate resumes degraded from the
        persisted ledger instead of refusing: a mid-blackout SIGKILL
        must not wedge its successor. The first successful sync
        re-validates the adopted token against the live parent."""
        store = ParentStore(
            api,
            namespace=str(fed.get("parent_namespace") or "") or None,
            name=str(fed.get("parent_name") or PARENT_LEASE_NAME),
        )
        gate = cls(
            store, region=str(fed["region"]), metrics=metrics,
            offline_grace_s=offline_grace_s, clock=clock,
        )
        try:
            parent = store.load()
        except KubeApiError as e:
            if not is_outage_error(e) or "escrow" not in fed:
                raise
            gate.generation = int(fed.get("generation") or 1)
            gate.digest = str(fed.get("digest") or "") or None
            gate.regions_total = max(int(fed.get("regions") or 1), 1)
            gate.escrow_balance = (
                int(fed["escrow"]) if fed.get("escrow") is not None else None
            )
            gate.acked_spend = {str(n) for n in fed.get("acked_spend") or []}
            gate.charged = {str(n) for n in fed.get("charged") or []}
            gate.region_budget = (
                int(fed["region_budget"])
                if fed.get("region_budget") is not None else None
            )
            gate.offline.note_failure()
            log.warning(
                "region %s: parent plane dark at resume; continuing "
                "degraded on persisted escrow (balance=%s, pending "
                "reconciliation=%d)", gate.region, gate.escrow_balance,
                len(gate.charged - gate.acked_spend),
            )
            return gate
        if parent is None:
            raise rollout_state.RolloutFenced(
                "regional record is federated but the parent record is "
                "gone; abort the regional record to discard it"
            )
        if fed.get("digest") and parent.digest != fed["digest"]:
            raise rollout_state.RolloutFenced(
                "federated parent record belongs to a different rollout "
                f"(digest {parent.digest} != recorded {fed['digest']})"
            )
        if parent.status == PARENT_ABORTED:
            raise rollout_state.RolloutFenced(
                "federated rollout was aborted "
                f"({parent.halted_reason or 'no reason recorded'}); "
                "abort the regional record to discard it"
            )
        gate.attach(parent)
        return gate

    def _count(self, outcome: str) -> None:
        if self.metrics is not None:
            self.metrics.record_federation_sync(outcome)

    @property
    def degraded(self) -> bool:
        """Whether this shard has declared parent-plane degraded mode
        (dark past the offline grace)."""
        return self._was_engaged

    def sync(
        self,
        spend,
        status: str = PARENT_IN_PROGRESS,
        done: int = 0,
        total: int = 0,
        halted_reason: str | None = None,
        lease_generation: int | None = None,
    ) -> dict:
        """One wave-boundary exchange with the parent.

        Pushes this region's budget spend (union-merged — exactly-once
        under CAS races), status and progress, re-reserves the escrow
        slice; returns ``{"spend": [global union], "halted": bool,
        "reason": ...}``. Raises ``RolloutFenced`` when the parent
        generation has advanced past this shard's token (force-abort),
        the parent is aborted, or the plan digest changed under it —
        the wedged-shard self-fence.

        TRANSPORT-level failures (the parent plane is dark) do not
        raise: the shard answers itself from the escrow ledger — keep
        rolling while dark spend stays within the escrowed slice, halt
        ``escrow-exhausted`` the moment it would exceed it. The next
        successful sync reconciles dark spend exactly-once (set union)
        and returns unused escrow."""
        if self.generation is None:
            raise rollout_state.RolloutFenced(
                "federation gate used before attach()"
            )
        regional_spend = sorted(set(spend))
        # Dark spend still pending reconciliation: everything the local
        # record charged since the last acknowledged global union.
        # Between syncs the local record only grows by LOCAL charges
        # (sibling spend arrives exclusively through the fold-down), so
        # this difference is exactly this region's attribution delta.
        pending = set(regional_spend) - self.acked_spend
        terminal = status in (PARENT_COMPLETE, PARENT_HALTED)
        regional_halt: dict = {"reason": None}

        def _merge(rec: ParentRecord) -> ParentRecord:
            regional_halt["reason"] = None
            self._guard(rec)
            rec.charge_budget(regional_spend)
            track = rec.failure_budget is not None
            charged = None
            if track:
                charged = sorted(
                    rec.region_charged(self.region) | self.charged | pending
                )
            rec.note_region(
                self.region, status, done, total,
                generation=lease_generation,
                charged=charged,
                synced_at=self.wall(),
            )
            rb = rec.region_budgets.get(self.region)
            if (
                rb is not None and charged is not None
                and len(charged) > rb and status != PARENT_HALTED
            ):
                regional_halt["reason"] = (
                    f"region {self.region}: {REGION_BUDGET_REASON} "
                    f"({len(charged)} > {rb})"
                )
            target = self._escrow_target(
                rec, set(charged or []),
                terminal=terminal or regional_halt["reason"] is not None,
            )
            if target is not None:
                rec.escrow[self.region] = target
            if status == PARENT_HALTED and rec.status == PARENT_IN_PROGRESS:
                if halted_reason and any(
                    r in halted_reason for r in _REGIONAL_ONLY_HALTS
                ):
                    # Regional-only halts (this region's escrow or
                    # heterogeneous cap ran dry) stop THIS shard without
                    # halting the federation: siblings' budgets are
                    # untouched, so they keep rolling.
                    pass
                else:
                    rec.status = PARENT_HALTED
                    rec.halted_reason = halted_reason or (
                        f"region {self.region} halted"
                    )
            elif rec.all_complete and rec.status == PARENT_IN_PROGRESS:
                rec.status = PARENT_COMPLETE
            return rec

        try:
            parent = self.store.update(_merge)
        except KubeApiError as e:
            if not is_outage_error(e):
                raise
            return self._offline_view(regional_spend, pending, status)
        reconnected = self.offline.note_success()
        self._was_engaged = False
        self.acked_spend = set(parent.budget_spend)
        if parent.failure_budget is not None:
            self.charged = parent.region_charged(self.region)
            self.escrow_balance = parent.escrow.get(self.region, 0)
        else:
            self.escrow_balance = None
        self._count("ok")
        if self.metrics is not None:
            self.metrics.set_federation_budget_spent(
                len(parent.budget_spend)
            )
            self.metrics.set_federation_offline_seconds(0.0)
            if self.escrow_balance is not None:
                self.metrics.set_federation_escrow(self.escrow_balance, 0)
        halted = parent.status == PARENT_HALTED and status != PARENT_HALTED
        reason = parent.halted_reason if halted else None
        if regional_halt["reason"] and status != PARENT_HALTED:
            halted = True
            reason = regional_halt["reason"]
        return {
            "spend": list(parent.budget_spend),
            "halted": halted,
            "reason": reason,
            "parent_status": parent.status,
            "offline": False,
            "degraded": False,
            "offline_edge": False,
            "reconnected": reconnected,
            "escrow": self.escrow_balance,
        }

    def _offline_view(
        self, regional_spend: list[str], pending: set[str], status: str
    ) -> dict:
        """The shard's self-answered sync while the parent plane is
        dark: local union only, halt verdict strictly from the escrow
        ledger. ``offline_edge`` flips True exactly once per outage, the
        first sync past the grace — the caller's cue to journal
        parent-offline and cross the parent-offline crash point."""
        self.offline.note_failure()
        engaged = self.offline.engaged
        edge = engaged and not self._was_engaged
        if edge:
            self._was_engaged = True
        self._count("offline")
        if self.metrics is not None:
            self.metrics.set_federation_offline_seconds(
                self.offline.offline_seconds
            )
            if self.escrow_balance is not None:
                self.metrics.set_federation_escrow(
                    self.escrow_balance, len(pending)
                )
        halted = False
        reason = None
        terminal = status in (PARENT_COMPLETE, PARENT_HALTED)
        if (
            not terminal
            and self.escrow_balance is not None
            and len(pending) > self.escrow_balance
        ):
            # The regional remainder of a heterogeneous cap IS the
            # escrow slice, so this one comparison covers both ledgers.
            halted = True
            reason = ESCROW_EXHAUSTED_REASON
        if pending:
            self.charged = self.charged | pending
        return {
            "spend": sorted(self.acked_spend | set(regional_spend)),
            "halted": halted,
            "reason": reason,
            "parent_status": PARENT_OFFLINE,
            "offline": True,
            "degraded": engaged,
            "offline_seconds": round(self.offline.offline_seconds, 3),
            "offline_edge": edge,
            "reconnected": False,
            "escrow": self.escrow_balance,
            "escrow_pending": len(pending),
        }


def describe_parent(
    parent: ParentRecord | None, wall=time.time,
    offline_grace_s: float | None = None,
) -> str:
    """One operator-readable block for ``tpu-cc-ctl status`` /
    ``rollout --regions`` output: global ledger, then per region its
    progress, escrow balance/heterogeneous cap, and last-sync age (a
    region silent past the offline grace is flagged STALE — the
    parent-side view of a possibly-degraded shard). The age is display
    only; fencing never reads it."""
    if parent is None:
        return "federation: no parent record"
    grace = (
        offline_grace_s if offline_grace_s is not None
        else federation_offline_grace_s()
    )
    escrowed = sum(parent.escrow.values())
    lines = [
        f"federation: mode={parent.mode} status={parent.status} "
        f"gen={parent.generation} digest={parent.digest} "
        f"budget_spend={len(parent.budget_spend)}"
        + (f"/{parent.failure_budget}" if parent.failure_budget is not None
           else "")
        + (f" escrowed={escrowed}" if parent.escrow else "")
    ]
    for name in sorted(parent.regions):
        r = parent.regions[name]
        line = (
            f"  region {name}: {r.get('status')} "
            f"{r.get('done')}/{r.get('total')} group(s)"
            + (f" gen={r.get('generation')}" if r.get("generation") else "")
        )
        if name in parent.region_budgets:
            line += (
                f" budget={len(parent.region_charged(name))}"
                f"/{parent.region_budgets[name]}"
            )
        if name in parent.escrow:
            line += f" escrow={parent.escrow[name]}"
        synced_at = r.get("synced_at")
        if synced_at is not None:
            age = max(0.0, wall() - float(synced_at))
            line += f" synced {age:.0f}s ago"
            if grace > 0 and age >= grace and r.get("status") not in (
                PARENT_COMPLETE, PARENT_HALTED,
            ):
                line += " (STALE — parent plane dark or shard dead?)"
        lines.append(line)
    if parent.halted_reason:
        lines.append(f"  halted: {parent.halted_reason}")
    return "\n".join(lines)
