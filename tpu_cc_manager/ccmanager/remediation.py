"""Failure containment: the per-node remediation escalation ladder.

The reference's only answer to a failed mode flip is a ``failed`` label and
an operator page (main.py:499-581); PR 2 added retries, breakers and a
health watchdog — but a *terminally* failing node still backoff-retried
forever, stayed eligible for rollouts and pool attestation, and kept its
ICI peers burning full barrier deadlines. This module adds the missing
layer: isolate a bad node fast, keep the rest of the pool converging.

The ladder, per node::

    backoff-retry  ->  device-reset  ->  runtime-restart  ->  quarantine

Each rung gets ``failures_per_step`` consecutive failed reconciles before
the ladder escalates; any successful reconcile resets it. The first rung
is the manager's existing backoff retry (no extra action); ``device-reset``
re-resets the chip set, ``runtime-restart`` bounces the TPU runtime
(:meth:`TpuCcBackend.restart_runtime`), and ``quarantine`` is terminal:

- a ``NoSchedule`` taint (:data:`~tpu_cc_manager.labels.QUARANTINE_TAINT_KEY`)
  keeps new workloads off the node,
- the :data:`~tpu_cc_manager.labels.QUARANTINED_LABEL` label makes the
  rolling orchestrator and pool attestation skip it (and the pool failure
  budget count it),
- ``cc.ready.state`` flips to ``false`` and a ``CCNodeQuarantined`` event
  is emitted,
- if the node is part of a multi-host slice, the slice barrier is aborted
  with a new fencing generation (slicecoord.fence_slice) so peers fail
  fast instead of timing out.

Ladder state (failure count, current step, quarantine flag) is persisted
in a node annotation, so a DaemonSet crash-restart resumes the ladder
instead of restarting it from rung zero — a terminally bad node cannot
dodge quarantine by crashing the agent.

Quarantine auto-lifts after a **probation window**: the PR-2 watchdog's
probes feed :meth:`RemediationLadder.note_probe`, and once the runtime has
reported healthy continuously for ``probation_s`` the taint/label are
removed, ready state is restored from the current mode.state, and the
ladder resets (``CCNodeUnquarantined`` event). Operators can force either
edge with ``tpu-cc-ctl quarantine`` / ``unquarantine``.
"""

from __future__ import annotations

import json
import logging
import time
from typing import Callable

from tpu_cc_manager.ccmanager import intent_journal as intent_mod
from tpu_cc_manager.ccmanager import slicecoord
from tpu_cc_manager.kubeclient.api import (
    KubeApi,
    KubeApiError,
    node_annotations,
    node_labels,
)
from tpu_cc_manager import labels as labels_mod
from tpu_cc_manager.labels import (
    CC_MODE_STATE_LABEL,
    CC_READY_STATE_LABEL,
    QUARANTINE_TAINT_KEY,
    QUARANTINED_LABEL,
    SLICE_ID_LABEL,
    label_safe,
    ready_state_for,
)
from tpu_cc_manager.tpudev.contract import TpuCcBackend, TpuError
from tpu_cc_manager.utils import metrics as metrics_mod
from tpu_cc_manager.utils import locks as locks_mod

log = logging.getLogger(__name__)

#: Ladder rungs, mild to terminal.
STEP_RETRY = "backoff-retry"
STEP_DEVICE_RESET = "device-reset"
STEP_RUNTIME_RESTART = "runtime-restart"
STEP_QUARANTINE = "quarantine"
STEPS = (STEP_RETRY, STEP_DEVICE_RESET, STEP_RUNTIME_RESTART, STEP_QUARANTINE)

#: Node annotation carrying the persisted ladder state (JSON). Wire name
#: centralized in labels.py (cclint surface contract); re-exported here.
REMEDIATION_ANNOTATION = labels_mod.REMEDIATION_ANNOTATION

#: Failure reasons that say nothing about THIS node's hardware: a fenced
#: or timed-out barrier is a PEER's failure (escalating here would cascade
#: one bad host into device resets and quarantine of its healthy
#: slice-mates), and an apiserver outage is nobody's hardware fault.
#: These never climb the ladder.
NON_ESCALATING_REASONS = frozenset({
    "barrier-fenced",
    "barrier-timeout",
    "apiserver-error",
})

#: Failure reasons that climb the ladder but must NOT trigger the
#: hardware rungs' actions: a drain timeout means workloads are still on
#: the chips — resetting them out from under the pods would destroy the
#: exact guarantee strict eviction refused to break. Sustained drain
#: failure still ends in quarantine (stop scheduling onto a node that
#: cannot drain), just without intermediate resets.
NO_HARDWARE_ACTION_REASONS = frozenset({"drain-timeout"})

QUARANTINE_TAINT = {
    "key": QUARANTINE_TAINT_KEY,
    "value": "true",
    "effect": "NoSchedule",
}

DEFAULT_FAILURES_PER_STEP = 2
DEFAULT_PROBATION_S = 300.0


def quarantined_nodes(nodes: list[dict]) -> list[str]:
    """Names of quarantined nodes in a listing, sorted (the rolling
    orchestrator's skip/budget predicate; pool attestation checks the
    label per-node inline while walking each node's labels anyway)."""
    return sorted(
        n["metadata"]["name"]
        for n in nodes
        if node_labels(n).get(QUARANTINED_LABEL) == "true"
    )


class RemediationLadder:
    """One node's escalating remediation state machine.

    ``emit_event`` matches CCManager._emit_node_event's signature; all
    label/taint writes are best-effort-logged but quarantine is only
    *recorded* when the label write (the part every consumer keys on)
    landed.
    """

    def __init__(
        self,
        api: KubeApi,
        node_name: str,
        backend: TpuCcBackend | None = None,
        failures_per_step: int = DEFAULT_FAILURES_PER_STEP,
        probation_s: float = DEFAULT_PROBATION_S,
        emit_event: Callable[[str, str, str], None] | None = None,
        metrics: metrics_mod.MetricsRegistry | None = None,
        clock: Callable[[], float] = time.monotonic,
        intents: "intent_mod.IntentJournal | None" = None,
    ) -> None:
        self.api = api
        self.node_name = node_name
        self.backend = backend
        # Node-local intent WAL: the hardware rungs journal a
        # KIND_REMEDIATION intent BEFORE touching the device (the cclint
        # journal-before-reset contract). None = unjournaled (tests,
        # CC_INTENT_JOURNAL=0), matching the manager's own degradation.
        self.intents = intents
        self.failures_per_step = max(1, failures_per_step)
        self.probation_s = probation_s
        self.emit_event = emit_event or (lambda *_: None)
        self.metrics = metrics if metrics is not None else metrics_mod.REGISTRY
        self.clock = clock
        self.failures = 0  # cclint: guarded-by(_lock)
        self.step = STEP_RETRY  # cclint: guarded-by(_lock)
        self.quarantined = False  # cclint: guarded-by(_lock)
        self.last_reason = ""  # cclint: guarded-by(_lock)
        # Confirmed fail-slow verdicts acted on (obs/failslow.py feed):
        # a NON-probe signal ladder — verdict 1 restarts the runtime,
        # verdict 2 quarantines reason=fail-slow. Persisted with the
        # rest of the ladder so an agent restart mid-escalation cannot
        # reset a gray node back to the cheap rung. Cleared when the
        # peer-relative stats recover (note_failslow_recovered) or on
        # unquarantine.
        self.failslow_signals = 0  # cclint: guarded-by(_lock)
        # Probation: monotonic timestamp of the first healthy probe of the
        # current healthy streak while quarantined; None = not in a streak.
        # In-memory only — an agent restart restarts probation, which errs
        # conservative (a crashing agent is itself a bad sign).
        self._healthy_since: float | None = None  # cclint: guarded-by(_lock)
        # The ladder is mutated from two threads — the watch loop
        # (note_failure/note_success) and the watchdog (note_probe →
        # unquarantine) — so every public mutator holds this lock; a
        # probation lift can no longer interleave with a failure note.
        self._lock = locks_mod.make_rlock("remediation")
        # Whether the persisted state has been read successfully; a failed
        # startup load is retried lazily so a quarantined node cannot slip
        # back to reconciling through one apiserver blip at boot.
        self._loaded = False
        self._load()
        self.metrics.set_quarantined(self.quarantined)

    # -- persistence -------------------------------------------------------

    def _load(self) -> None:  # cclint: requires(_lock)
        """Resume ladder state from the node annotation (agent restart must
        not reset a terminally bad node back to rung zero)."""
        try:
            raw = node_annotations(self.api.get_node(self.node_name)).get(
                REMEDIATION_ANNOTATION
            )
        except KubeApiError as e:
            log.warning(
                "remediation: could not load ladder state (%s); will retry "
                "before acting", e,
            )
            return
        self._loaded = True
        if not raw:
            return
        try:
            state = json.loads(raw)
            self.failures = int(state.get("failures", 0))
            step = str(state.get("step", STEP_RETRY))
            self.step = step if step in STEPS else STEP_RETRY
            self.quarantined = bool(state.get("quarantined", False))
            self.last_reason = str(state.get("reason", ""))
            self.failslow_signals = int(state.get("failslow", 0))
        except (ValueError, TypeError) as e:
            log.warning("remediation: corrupt ladder annotation (%s); reset", e)
            return
        if self.failures or self.quarantined:
            log.info(
                "remediation: resumed ladder state from annotation "
                "(failures=%d step=%s quarantined=%s)",
                self.failures, self.step, self.quarantined,
            )

    def _persist(self) -> None:  # cclint: requires(_lock)
        """Best-effort write-through of the ladder state; a lost write costs
        at most one rung of progress after a crash-restart."""
        value: str | None
        if (
            not self.failures and not self.quarantined
            and not self.failslow_signals
        ):
            value = None  # clean state: drop the annotation entirely
        else:
            value = json.dumps({
                "failures": self.failures,
                "step": self.step,
                "quarantined": self.quarantined,
                "reason": self.last_reason,
                "failslow": self.failslow_signals,
                "ts": int(time.time()),
            }, sort_keys=True)
        try:
            self.api.patch_node_annotations(
                self.node_name, {REMEDIATION_ANNOTATION: value}
            )
        except KubeApiError as e:
            log.warning("remediation: could not persist ladder state: %s", e)

    def _ensure_loaded(self) -> None:  # cclint: requires(_lock)
        """Lazy retry of a failed startup load: a quarantined node whose
        agent rebooted through an apiserver blip must re-learn its
        quarantine before any ladder decision runs against clean state."""
        if not self._loaded:
            self._load()
            if self._loaded:
                self.metrics.set_quarantined(self.quarantined)

    # -- ladder ------------------------------------------------------------

    def step_for_failures(self, failures: int) -> str:
        """Which rung failure number ``failures`` (1-based) lands on."""
        if failures <= 0:
            return STEP_RETRY
        return STEPS[min((failures - 1) // self.failures_per_step, len(STEPS) - 1)]

    def note_success(self) -> None:
        """A reconcile converged: the ladder resets (quarantine does NOT
        auto-lift here — release goes through probation or the operator)."""
        with self._lock:
            self._ensure_loaded()
            if not self.failures and not self.quarantined:
                return
            if self.quarantined:
                # The mode label may have been reconciled while quarantined;
                # the ladder stays latched until probation/operator lifts.
                return
            log.info(
                "remediation: reconcile succeeded; ladder reset from "
                "(failures=%d step=%s)", self.failures, self.step,
            )
            self.failures = 0
            self.step = STEP_RETRY
            self._persist()

    def note_failure(self, reason: str = "") -> str:
        """One failed reconcile: count it, run the rung's action, persist.
        Returns the rung that ran."""
        with self._lock:
            self._ensure_loaded()
            return self._note_failure_locked(reason)

    def _note_failure_locked(self, reason: str) -> str:  # cclint: requires(_lock)
        if self.quarantined:
            return STEP_QUARANTINE  # already contained; nothing to escalate
        if reason in NON_ESCALATING_REASONS:
            log.info(
                "remediation: failure reason %s is not this node's fault; "
                "ladder not escalated", reason,
            )
            return self.step
        self.failures += 1
        self.last_reason = reason
        step = self.step_for_failures(self.failures)
        escalated = step != self.step
        self.step = step
        outcome = "ok"
        hardware_ok = reason not in NO_HARDWARE_ACTION_REASONS
        try:
            if step == STEP_DEVICE_RESET and hardware_ok:
                self._device_reset()
            elif step == STEP_RUNTIME_RESTART and hardware_ok:
                self._runtime_restart()
            elif step == STEP_QUARANTINE:
                self.quarantine(reason=reason or "remediation-ladder")
            elif not hardware_ok and step in (
                STEP_DEVICE_RESET, STEP_RUNTIME_RESTART
            ):
                # The node cannot drain: a reset would rip the chips out
                # from under still-running workloads (the strict-eviction
                # guarantee). Count the failure, skip the action.
                outcome = "skipped"
        except (TpuError, KubeApiError) as e:
            outcome = "failed"
            log.error(
                "remediation step %s failed on %s: %s", step, self.node_name, e
            )
        self.metrics.record_remediation_step(
            step, "escalated" if escalated and outcome == "ok" else outcome
        )
        if step != STEP_QUARANTINE:
            log.warning(
                "remediation: failure %d (%s) on %s -> step %s (%s)",
                self.failures, reason or "unspecified", self.node_name,
                step, outcome,
            )
        self._persist()
        return step

    def note_failslow(self, deviation: float | None = None) -> str:
        """One CONFIRMED peer-relative fail-slow verdict
        (obs/failslow.py): the gray-failure entry into the ladder.
        Unlike note_failure this is a non-probe signal — the watchdog
        is green throughout, nothing ever errored — so it enters at the
        hardware rungs directly: the first confirmed verdict restarts
        the TPU runtime (the cheapest action that un-wedges a degraded
        runtime), a re-concluded verdict after that quarantines with
        ``reason=fail-slow`` (probation plus recovered peer-relative
        stats lift it). Returns the rung that ran."""
        with self._lock:
            self._ensure_loaded()
            if self.quarantined:
                return STEP_QUARANTINE  # already contained
            self.failslow_signals += 1
            self.last_reason = "fail-slow"
            if self.failslow_signals == 1:
                outcome = "ok"
                try:
                    if self.backend is not None:
                        self._runtime_restart()
                    else:
                        outcome = "skipped"
                except (TpuError, KubeApiError) as e:
                    outcome = "failed"
                    log.error(
                        "remediation: fail-slow runtime restart failed on "
                        "%s: %s", self.node_name, e,
                    )
                self.metrics.record_remediation_step(
                    STEP_RUNTIME_RESTART, outcome
                )
                log.warning(
                    "remediation: fail-slow verdict %d on %s "
                    "(deviation=%s) -> %s (%s)",
                    self.failslow_signals, self.node_name,
                    f"{deviation:.2f}x" if deviation else "n/a",
                    STEP_RUNTIME_RESTART, outcome,
                )
                self._persist()
                return STEP_RUNTIME_RESTART
            self._quarantine_locked(reason="fail-slow", manual=False)
            return STEP_QUARANTINE

    def note_failslow_recovered(self) -> None:
        """The vetter CLEARED the node before the ladder reached
        quarantine (peer-relative stats recovered — e.g. the runtime
        restart fixed it): forget the escalation so the next confirmed
        verdict, if any, starts from the cheap rung again. A
        quarantined node is NOT released here — that goes through
        probation (note_probe) or the operator, same as every other
        quarantine."""
        with self._lock:
            self._ensure_loaded()
            if self.quarantined or not self.failslow_signals:
                return
            log.info(
                "remediation: fail-slow suspicion cleared on %s after %d "
                "verdict(s); escalation reset", self.node_name,
                self.failslow_signals,
            )
            self.failslow_signals = 0
            self._persist()

    def _journal_hardware_intent(self, op: str) -> str | None:
        """Journal-before-reset: a KIND_REMEDIATION intent fsync'd BEFORE
        the rung's disruptive work. No intent record, no hardware action
        (same discipline as the manager's transition bracket); replay of
        an open one simply closes it — the backend's pending markers and
        the persisted ladder annotation already carry recovery."""
        if self.intents is None:
            return None
        try:
            return self.intents.begin(
                intent_mod.KIND_REMEDIATION, op=op, node=self.node_name
            )
        except intent_mod.JournalError as e:
            raise TpuError(
                f"could not journal remediation {op} intent: {e}"
            ) from e

    def _journal_close(self, txn: str | None, ok: bool) -> None:
        if txn is None or self.intents is None:
            return
        try:
            if ok:
                self.intents.commit(txn)
            else:
                self.intents.abort(txn)
        except intent_mod.JournalError as e:
            log.warning("could not close remediation intent %s: %s", txn, e)

    def _device_reset(self) -> None:
        if self.backend is None:
            raise TpuError("no backend wired for device-reset remediation")
        chips = self.backend.discover().chips
        log.warning(
            "remediation: re-resetting %d chip(s) on %s", len(chips),
            self.node_name,
        )
        txn = self._journal_hardware_intent("device-reset")
        try:
            self.backend.reset(chips)
        except Exception:
            # Ordinary failures abort the intent; a modeled SIGKILL
            # (BaseException) escapes with it OPEN — replay closes it.
            self._journal_close(txn, ok=False)
            raise
        self._journal_close(txn, ok=True)

    def _runtime_restart(self) -> None:
        if self.backend is None:
            raise TpuError("no backend wired for runtime-restart remediation")
        log.warning("remediation: restarting TPU runtime on %s", self.node_name)
        txn = self._journal_hardware_intent("runtime-restart")
        try:
            self.backend.restart_runtime()
        except Exception:
            self._journal_close(txn, ok=False)
            raise
        self._journal_close(txn, ok=True)

    # -- quarantine --------------------------------------------------------

    def quarantine(self, reason: str = "manual", manual: bool = False) -> None:
        """Contain the node: taint + label + ready=false + event, and fence
        any in-flight slice barrier. Idempotent."""
        with self._lock:
            self._ensure_loaded()
            self._quarantine_locked(reason, manual)

    def _quarantine_locked(self, reason: str, manual: bool) -> None:  # cclint: requires(_lock)
        if self.quarantined:
            return
        # The label patch is the authoritative edge (rollouts, attestation
        # and the budget all key on it) — it runs first and a failure
        # propagates so the ladder retries on the next failed reconcile.
        self.api.patch_node_labels(self.node_name, {
            QUARANTINED_LABEL: "true",
            CC_READY_STATE_LABEL: "false",
        })
        self.quarantined = True
        self._healthy_since = None
        self.last_reason = reason
        try:
            self.api.patch_node_taints(
                self.node_name, [dict(QUARANTINE_TAINT)], []
            )
        except KubeApiError as e:
            # Clients without taint support (or a lost patch) still get the
            # control-plane containment from the label; log loudly.
            log.warning(
                "remediation: could not apply quarantine taint on %s: %s",
                self.node_name, e,
            )
        self._fence_own_slice(reason)
        self.metrics.set_quarantined(True)
        if manual:
            self.metrics.record_remediation_step(STEP_QUARANTINE, "manual")
        log.error(
            "node %s QUARANTINED (%s): NoSchedule taint + %s=true, "
            "ready.state=false; probation window %.0fs",
            self.node_name, reason, QUARANTINED_LABEL, self.probation_s,
        )
        self.emit_event(
            "Warning", "CCNodeQuarantined",
            f"node quarantined by the remediation ladder ({reason}); "
            f"NoSchedule taint applied, probation {self.probation_s:.0f}s",
        )
        self._persist()

    def unquarantine(self, reason: str = "manual") -> None:
        """Release the node: remove taint + label, restore ready state from
        the current mode.state, reset the ladder. Idempotent."""
        with self._lock:
            self._unquarantine_locked(reason)

    def _unquarantine_locked(self, reason: str) -> None:  # cclint: requires(_lock)
        try:
            state = node_labels(self.api.get_node(self.node_name)).get(
                CC_MODE_STATE_LABEL, ""
            )
        except KubeApiError:
            state = ""
        self.api.patch_node_labels(self.node_name, {
            QUARANTINED_LABEL: None,
            CC_READY_STATE_LABEL: ready_state_for(state),
        })
        try:
            self.api.patch_node_taints(
                self.node_name, [], [QUARANTINE_TAINT_KEY]
            )
        except KubeApiError as e:
            log.warning(
                "remediation: could not remove quarantine taint on %s: %s",
                self.node_name, e,
            )
        was = self.quarantined
        self.quarantined = False
        self._healthy_since = None
        self.failures = 0
        self.step = STEP_RETRY
        self.failslow_signals = 0
        self.metrics.set_quarantined(False)
        if was:
            log.warning(
                "node %s unquarantined (%s); ladder reset", self.node_name,
                reason,
            )
            self.emit_event(
                "Normal", "CCNodeUnquarantined",
                f"quarantine lifted ({reason}); node rejoins the pool",
            )
        self._persist()

    def condemn(self, reason: str = "watchdog-condemned") -> None:
        """Fence this host's slice WITHOUT quarantining (the watchdog's
        demote edge: peers mid-barrier must not wait out the deadline on a
        host that just went unhealthy)."""
        self._fence_own_slice(reason)

    def _fence_own_slice(self, reason: str) -> None:
        """Abort any in-flight barrier of this host's slice with a new
        fencing generation. Best-effort: containment of THIS node never
        fails because peers couldn't be told."""
        slice_id = None
        if self.backend is not None:
            try:
                topo = self.backend.discover()
                if not topo.is_multi_host:
                    return  # no peers to fence out
                slice_id = topo.slice_id
            except TpuError as e:
                log.warning(
                    "remediation: discovery failed (%s); fencing from the "
                    "slice label instead", e,
                )
        if slice_id is None:
            # No device layer here (the operator CLI, or discovery down):
            # the published slice-membership label is the peers' discovery
            # medium anyway, so fence through it. Fencing a single-host
            # slice is harmless — nobody is listening.
            try:
                slice_id = node_labels(
                    self.api.get_node(self.node_name)
                ).get(SLICE_ID_LABEL)
            except KubeApiError as e:
                log.warning("remediation: cannot read slice label: %s", e)
            if not slice_id:
                return
        try:
            slicecoord.fence_slice(
                self.api, self.node_name, slice_id, reason=reason,
                metrics=self.metrics,
            )
        except KubeApiError as e:
            log.warning(
                "remediation: could not fence slice %s: %s", slice_id, e
            )

    # -- probation ---------------------------------------------------------

    def note_probe(self, healthy: bool) -> None:
        """Watchdog probe feed: continuous health for ``probation_s`` while
        quarantined lifts the quarantine."""
        with self._lock:
            self._ensure_loaded()
            if not self.quarantined:
                return
            if not healthy:
                if self._healthy_since is not None:
                    log.info(
                        "remediation: probation reset on %s (probe unhealthy)",
                        self.node_name,
                    )
                self._healthy_since = None
                return
            now = self.clock()
            if self._healthy_since is None:
                self._healthy_since = now
                return
            if now - self._healthy_since >= self.probation_s:
                self._unquarantine_locked(reason="probation-elapsed")

    # -- reporting ---------------------------------------------------------

    def describe(self) -> str:
        """One label-safe token for `tpu-cc-ctl status` notes."""
        with self._lock:
            if self.quarantined:
                return "quarantined"
            if self.failslow_signals:
                return f"fail-slow({self.failslow_signals})"
            if self.failures:
                return f"{self.step}({self.failures})"
            return ""


def describe_annotation(raw: str | None) -> str:
    """Render a persisted ladder annotation for status output ("" when
    clean/absent/corrupt)."""
    if not raw:
        return ""
    try:
        state = json.loads(raw)
    except ValueError:
        return "remediation:corrupt"
    if state.get("quarantined"):
        reason = label_safe(str(state.get("reason") or "")) or "unknown"
        return f"quarantined({reason})"
    failures = state.get("failures") or 0
    step = state.get("step") or STEP_RETRY
    return f"remediation:{step}({failures})" if failures else ""


def from_env(
    api: KubeApi,
    node_name: str,
    backend: TpuCcBackend | None = None,
    emit_event: Callable[[str, str, str], None] | None = None,
    metrics: metrics_mod.MetricsRegistry | None = None,
    intents: "intent_mod.IntentJournal | None" = None,
) -> RemediationLadder | None:
    """CLI wiring: CC_REMEDIATION_FAILURES_PER_STEP (0 disables the whole
    ladder), CC_QUARANTINE_PROBATION_S."""
    import os

    per_step = int(os.environ.get(
        "CC_REMEDIATION_FAILURES_PER_STEP", str(DEFAULT_FAILURES_PER_STEP)
    ))
    if per_step <= 0:
        log.info("remediation ladder disabled (CC_REMEDIATION_FAILURES_PER_STEP<=0)")
        return None
    return RemediationLadder(
        api,
        node_name,
        backend=backend,
        failures_per_step=per_step,
        probation_s=float(os.environ.get(
            "CC_QUARANTINE_PROBATION_S", str(DEFAULT_PROBATION_S)
        )),
        emit_event=emit_event,
        metrics=metrics,
        intents=intents,
    )
