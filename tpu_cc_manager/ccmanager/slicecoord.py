"""Slice-wide multi-host commit coordination.

The reference's PPCIe mode is fabric-atomic within one OS image: stage ALL
devices, then reset ALL together "so the NVLink fabric is configured
consistently" (reference main.py:362-368). A multi-host TPU slice spreads
that fabric across machines, so the stage-all/reset-all invariant needs a
cross-host barrier: **no host of an ICI slice may reset its runtime before
every host of the slice is staged and drained.**

The barrier runs over node labels — the same medium the rest of the control
plane uses for desired/actual state — so a crash at any point leaves labels
describing reality (SURVEY.md §7(c)):

- ``cloud.google.com/tpu-cc.slice.staged`` — published by each host after it
  has drained its components and staged the new mode on its chips. Value is
  the staged mode. Cleared when the host finishes (or aborts) the
  transition, so a lingering marker means "host is mid-transition".
- ``cloud.google.com/tpu-cc.slice.commit`` — published by the slice leader
  (``host_index == 0``) on its own node once it observes every host of the
  slice staged. Followers reset only after BOTH observing all hosts staged
  AND seeing the leader's commit marker. The leader clears the marker after
  the barrier completes (best-effort; a stale marker alone can never trigger
  a reset because followers always re-check full staging themselves).

Peer discovery uses the slice-membership label
(:data:`~tpu_cc_manager.labels.SLICE_ID_LABEL`): each host publishes it at
barrier entry, and the barrier is complete when ``num_hosts`` nodes carry the
slice id with a matching staged marker. ``num_hosts`` comes from the device
topology, so a half-visible slice can never commit.

Failure semantics:

- Barrier timeout → :class:`BarrierTimeout` (a :class:`TpuError`): the
  reconcile fails, the host clears its own staged marker (it is about to
  re-admit components, so "staged and drained" is no longer true) and labels
  itself ``failed``. No hardware was touched.
- Crash mid-barrier → the staged marker stays behind; peers time out and
  fail soft. When the crashed host's agent restarts, the apply re-runs and
  re-publishes the marker (idempotent), and the barrier converges.
- Leader crash after publishing commit → followers that saw the marker
  reset (the fabric transition was already decided); the restarted leader's
  re-apply clears its stale marker at barrier entry and re-runs the
  protocol against its peers' already-committed state.

Dead-peer fencing (failure containment, ccmanager/remediation.py):

A host that dies mid-barrier used to cost every peer the full barrier
deadline. When a host is condemned — quarantined by the remediation
ladder, or watchdog-condemned — it (or the operator) bumps the slice's
**fencing generation** (``…slice.fence``, an integer label on the
condemned node). Every barrier round is entered at the generation current
at publish time, carried in ``…slice.staged-gen`` / ``…slice.commit-gen``:

- peers polling the barrier see a fence generation NEWER than their own
  round and abort immediately with :class:`BarrierFenced` — fail fast,
  well under the barrier deadline;
- a stale agent from a pre-fence round can neither complete the aborted
  barrier (its commit marker carries the old generation, which no
  current-round follower accepts, and its own next poll aborts) nor
  re-stage it (its old-generation staged marker never counts as ready
  for the new round). Re-entering the barrier afresh reads the CURRENT
  generation — a fresh round is always allowed.
"""

from __future__ import annotations

import logging

from tpu_cc_manager.kubeclient.api import (
    KubeApi,
    KubeApiError,
    caller_retry_attempts,
    classify_kube_error,
    node_labels,
)
from tpu_cc_manager import labels as labels_mod
from tpu_cc_manager.labels import (
    CC_MODE_STATE_LABEL,
    SLICE_ID_LABEL,
    label_safe,
)
from tpu_cc_manager.obs import trace as obs_trace
from tpu_cc_manager.tpudev.contract import SliceTopology, TpuError
from tpu_cc_manager.utils import metrics as metrics_mod
from tpu_cc_manager.utils import retry as retry_mod

log = logging.getLogger(__name__)

# Wire names centralized in labels.py (cclint surface contract);
# re-exported here so the barrier's public API is unchanged.
SLICE_STAGED_LABEL = labels_mod.SLICE_STAGED_LABEL
SLICE_COMMIT_LABEL = labels_mod.SLICE_COMMIT_LABEL
# Dead-peer fencing: the slice's current fencing generation (integer),
# bumped on the condemned node; rounds entered at an older generation
# abort fast and can neither complete nor re-stage.
SLICE_FENCE_LABEL = labels_mod.SLICE_FENCE_LABEL
# Which generation a host's staged / commit marker belongs to.
SLICE_STAGED_GEN_LABEL = labels_mod.SLICE_STAGED_GEN_LABEL
SLICE_COMMIT_GEN_LABEL = labels_mod.SLICE_COMMIT_GEN_LABEL

DEFAULT_BARRIER_TIMEOUT_S = 300.0
# How long the leader lingers after its own transition for peers to clear
# their staged markers before it retires the commit marker.
DEFAULT_COMPLETE_TIMEOUT_S = 60.0


class BarrierTimeout(TpuError):
    """The slice barrier did not form (or complete) in time."""


class BarrierFenced(TpuError):
    """The barrier round was aborted by a newer fencing generation (a peer
    was condemned mid-barrier); the caller fails fast instead of burning
    the barrier deadline."""


def _gen_of(labels: dict, key: str) -> int:
    """Integer generation from a label value (absent/garbled -> 0, so
    pre-fencing peers interoperate as generation 0)."""
    try:
        return int(labels.get(key) or 0)
    except (TypeError, ValueError):
        return 0


def fence_generation(nodes: list[dict]) -> int:
    """The slice's current fencing generation: the max fence label across
    its nodes (any node may carry it — normally the condemned one)."""
    return max(
        (_gen_of(node_labels(n), SLICE_FENCE_LABEL) for n in nodes),
        default=0,
    )


def fence_slice(
    api: KubeApi,
    node_name: str,
    slice_id: str,
    reason: str = "",
    metrics: "metrics_mod.MetricsRegistry | None" = None,
) -> int:
    """Abort any in-flight barrier round of ``slice_id`` by bumping the
    fencing generation on ``node_name`` (the condemned host — the caller
    holds patch RBAC on it). Also withdraws that host's own staged marker:
    a condemned host is by definition not "staged and drained". Returns
    the new generation. Raises KubeApiError on failure — the caller
    decides whether fencing is best-effort."""
    slice_value = label_safe(slice_id)
    nodes = api.list_nodes(f"{SLICE_ID_LABEL}={slice_value}")
    generation = fence_generation(nodes) + 1
    api.patch_node_labels(node_name, {
        # Peers discover the fence through the slice-membership listing, so
        # membership is (re)published with it — a host condemned before its
        # first successful reconcile must not carry an invisible fence.
        SLICE_ID_LABEL: slice_value,
        SLICE_FENCE_LABEL: str(generation),
        SLICE_STAGED_LABEL: None,
        SLICE_STAGED_GEN_LABEL: None,
    })
    (metrics if metrics is not None else metrics_mod.REGISTRY).record_barrier_fenced()
    log.warning(
        "slice %s FENCED at generation %d by %s%s: in-flight barrier "
        "rounds abort; peers fail fast",
        slice_id, generation, node_name, f" ({reason})" if reason else "",
    )
    return generation


def fence_departed_peer(
    api: KubeApi,
    node_name: str,
    slice_id: str,
    reason: str = "preempted",
    metrics: "metrics_mod.MetricsRegistry | None" = None,
) -> int | None:
    """Fence the slice on behalf of a host that is about to DEPART
    (platform preemption, autoscaler reclaim): its peers mid-barrier must
    abort fast with BarrierFenced instead of burning the barrier deadline
    waiting for a staged marker whose owner is being reclaimed. Unlike
    :func:`fence_slice`, failures are swallowed — the departing host is
    racing a hard kill deadline and a fencing hiccup must not consume the
    seconds the handoff publish still needs (peers then merely degrade to
    the old timeout behavior). Returns the new generation, or None."""
    try:
        return fence_slice(
            api, node_name, slice_id, reason=reason, metrics=metrics
        )
    except KubeApiError as e:
        log.warning(
            "could not fence slice %s for departing host %s (%s); peers "
            "fall back to the barrier timeout", slice_id, node_name, e,
        )
        return None


class SliceBarrier:
    """One host's participation in one slice-wide commit round."""

    def __init__(
        self,
        api: KubeApi,
        node_name: str,
        topo: SliceTopology,
        timeout_s: float = DEFAULT_BARRIER_TIMEOUT_S,
        poll_interval_s: float = 1.0,
        complete_timeout_s: float = DEFAULT_COMPLETE_TIMEOUT_S,
        informer=None,
    ) -> None:
        self.api = api
        self.node_name = node_name
        self.topo = topo
        # Peer listing source (ccmanager/informer.py): with an informer
        # scoped to this slice's membership label, every barrier poll is
        # a local cache read — N hosts × barrier-deadline seconds of
        # 1/s peer listings stop hitting the apiserver. The informer's
        # slice index keys on the RAW label value, which is exactly
        # label_safe(slice_id) — the same value the membership label
        # carries.
        self.informer = informer
        self.timeout_s = timeout_s
        self.poll_interval_s = poll_interval_s
        self.complete_timeout_s = complete_timeout_s
        self.slice_label_value = label_safe(topo.slice_id)
        # The fencing generation this round was entered at (publish_staged
        # reads the slice's current generation). A newer generation
        # observed while waiting aborts the round with BarrierFenced.
        self.generation = 0
        # Transient-failure policy for the peer listing: short ladder (the
        # outer barrier deadline is authoritative) through the shared
        # jittered backoff instead of the old warn-and-poll-again. One
        # attempt when the client already retries internally (RestKube) —
        # exactly one ladder per logical call.
        self.retry_policy = retry_mod.RetryPolicy(
            max_attempts=caller_retry_attempts(api),
            base_delay_s=min(1.0, max(0.01, poll_interval_s)),
            max_delay_s=max(1.0, poll_interval_s * 4),
        )

    @property
    def is_leader(self) -> bool:
        return self.topo.host_index == 0

    # ------------------------------------------------------------------

    def publish_staged(self, mode: str) -> None:
        """Advertise "this host is drained and staged for ``mode``".

        Also publishes slice membership (peer discovery does not depend on a
        previous successful reconcile) and clears any commit marker this
        node owns from an earlier, possibly crashed, round.

        The round is entered at the slice's CURRENT fencing generation
        (read from the peers before publishing) and the staged marker is
        stamped with it — a marker left behind by a pre-fence round can
        never satisfy the current round's readiness count.
        """
        try:
            self.generation = fence_generation(self._slice_nodes())
        except KubeApiError as e:
            # Peer listing down at entry: enter at the last generation this
            # process saw (0 for a fresh barrier). Safe — a stale entry is
            # fenced out on the first successful poll.
            log.warning(
                "slice barrier: could not read fence generation (%s); "
                "entering at generation %d", e, self.generation,
            )
        self.api.patch_node_labels(
            self.node_name,
            {
                SLICE_ID_LABEL: self.slice_label_value,
                SLICE_STAGED_LABEL: mode,
                SLICE_STAGED_GEN_LABEL: str(self.generation),
                SLICE_COMMIT_LABEL: None,
                SLICE_COMMIT_GEN_LABEL: None,
            },
        )
        log.info(
            "slice %s host %d/%d: staged marker published (mode=%s gen=%d)",
            self.topo.slice_id, self.topo.host_index, self.topo.num_hosts,
            mode, self.generation,
        )

    def _slice_nodes(self) -> list[dict]:
        # Only a SYNCED cache may answer: an informer whose first listing
        # hasn't landed (start() returns after its sync wait even on
        # timeout) would silently report zero peers — publish_staged would
        # enter at fence generation 0 on a slice whose real generation is
        # higher, and every poll after sync would abort with a spurious
        # BarrierFenced. Unsynced degrades to the legacy listing path,
        # which raises on failure and lets callers keep last-known state.
        if self.informer is not None and self.informer.synced:
            return self.informer.slice_members(self.slice_label_value)
        return self.retry_policy.call(
            lambda: self.api.list_nodes(
                f"{SLICE_ID_LABEL}={self.slice_label_value}"
            ),
            op="barrier.list_peers",
            classify=classify_kube_error,
        )

    def await_commit(self, mode: str) -> None:
        """Block until this host may reset.

        A peer counts as *ready* when it is staged for ``mode`` — or when
        its actual-state label already reports ``mode``, i.e. it committed
        in an earlier round and this host is a recovering straggler (a crash
        mid-barrier must not wedge the slice: the survivors completed and
        cleared their staged markers, so staging alone could never re-form).

        Every host requires all ``num_hosts`` peers ready. The leader then
        publishes the commit marker and proceeds; followers additionally
        wait for a commit marker — the serialization point that stops a
        follower from resetting while a peer that briefly staged is already
        timing out and re-admitting its components. A follower whose peers
        have ALL already committed proceeds without a marker (the fabric
        transition was decided in the round it missed).
        """
        with obs_trace.span(
            "barrier.await_commit",
            slice=self.topo.slice_id,
            host_index=self.topo.host_index,
            num_hosts=self.topo.num_hosts,
            leader=self.is_leader,
        ):
            self._await_commit(mode)

    def _await_commit(self, mode: str) -> None:
        # Closure state across polls: the commit marker may be observed on
        # an earlier poll than the one where all hosts read ready, and the
        # timeout message reports the last observed readiness.
        state = {"committed_seen": False, "ready": None}

        def barrier_formed() -> bool:
            try:
                nodes = self._slice_nodes()
            except KubeApiError as e:
                # The retry policy already burned its short ladder; keep
                # polling — the barrier deadline is authoritative.
                log.warning("slice barrier: peer listing failed (%s); retrying", e)
                return False
            self._check_fence(nodes, mode)  # raises BarrierFenced
            ready, peers_committed = [], []
            for n in nodes:
                labels = node_labels(n)
                name = n["metadata"]["name"]
                already = labels.get(CC_MODE_STATE_LABEL) == mode
                staged_current = (
                    labels.get(SLICE_STAGED_LABEL) == mode
                    # A marker from a pre-fence round never counts: its
                    # host must re-enter at the current generation.
                    and _gen_of(labels, SLICE_STAGED_GEN_LABEL)
                    >= self.generation
                )
                if staged_current or already:
                    ready.append(name)
                if already and name != self.node_name:
                    peers_committed.append(name)
            state["ready"] = ready
            state["committed_seen"] = state["committed_seen"] or any(
                node_labels(n).get(SLICE_COMMIT_LABEL) == mode
                # A stale leader's pre-fence commit marker must not let a
                # current-round follower reset.
                and _gen_of(node_labels(n), SLICE_COMMIT_GEN_LABEL)
                >= self.generation
                for n in nodes
            )
            all_ready = len(ready) >= self.topo.num_hosts
            if all_ready and self.is_leader:
                self.api.patch_node_labels(
                    self.node_name,
                    {
                        SLICE_COMMIT_LABEL: mode,
                        SLICE_COMMIT_GEN_LABEL: str(self.generation),
                    },
                )
                log.info(
                    "slice %s: all %d host(s) ready; leader committing "
                    "mode=%s (gen=%d)",
                    self.topo.slice_id, self.topo.num_hosts, mode,
                    self.generation,
                )
                return True
            if all_ready and (
                state["committed_seen"]
                or len(peers_committed) >= self.topo.num_hosts - 1
            ):
                log.info(
                    "slice %s host %d: all ready (%s); committing mode=%s",
                    self.topo.slice_id, self.topo.host_index,
                    "leader marker" if state["committed_seen"]
                    else "peers already committed",
                    mode,
                )
                return True
            log.debug(
                "slice %s barrier: %d/%d ready, commit=%s",
                self.topo.slice_id, len(ready), self.topo.num_hosts,
                state["committed_seen"],
            )
            return False

        if not retry_mod.poll_until(
            barrier_formed, self.timeout_s, self.poll_interval_s
        ):
            ready = state["ready"]
            raise BarrierTimeout(
                f"slice {self.topo.slice_id}: barrier for mode {mode} did "
                f"not form within {self.timeout_s:.0f}s "
                f"({len(ready) if ready is not None else '?'}"
                f"/{self.topo.num_hosts} hosts ready)"
            )

    def _check_fence(self, nodes: list[dict], mode: str) -> None:
        """Raise BarrierFenced when the slice's fencing generation moved
        past this round's — a peer was condemned; fail fast."""
        current = fence_generation(nodes)
        if current > self.generation:
            raise BarrierFenced(
                f"slice {self.topo.slice_id}: barrier for mode {mode} "
                f"aborted — fencing generation advanced to {current} "
                f"(this round entered at {self.generation}); a peer was "
                "condemned mid-barrier"
            )

    def clear_staged(self) -> None:
        """Withdraw this host's staged marker (it is either done or about
        to re-admit components — either way no longer "staged and
        drained"). Best-effort."""
        try:
            self.api.patch_node_labels(self.node_name, {
                SLICE_STAGED_LABEL: None,
                SLICE_STAGED_GEN_LABEL: None,
            })
        except KubeApiError as e:
            log.warning("slice barrier: could not clear staged marker: %s", e)

    def abort(self) -> None:
        self.clear_staged()

    def complete(self, mode: str) -> None:
        """Retire the barrier. The caller runs this AFTER re-admitting
        components (manager.set_cc_mode), so the leader's bounded wait for
        peers never extends the drain window — it only delays the leader's
        own next watch iteration.

        The leader waits for every peer's staged marker to clear before
        retiring the commit marker: clearing it too early would strand
        followers still polling for it. A leftover marker is harmless —
        followers never act on a commit marker without re-verifying full
        staging — and is cleared at the next barrier entry.
        """
        self.clear_staged()  # idempotent; normally already cleared
        if not self.is_leader:
            return
        with obs_trace.span(
            "barrier.complete", slice=self.topo.slice_id, leader=True
        ):
            self._complete_as_leader(mode)

    def _complete_as_leader(self, mode: str) -> None:
        fenced = {"hit": False}

        def peers_cleared() -> bool:
            try:
                nodes = self._slice_nodes()
            except KubeApiError:
                return False
            if fence_generation(nodes) > self.generation:
                # This round was fenced: a stale leader must not keep
                # driving completion — retire its own (old-generation)
                # commit marker and get out of the new round's way.
                fenced["hit"] = True
                return True
            return not any(
                node_labels(n).get(SLICE_STAGED_LABEL) == mode for n in nodes
            )

        if not retry_mod.poll_until(
            peers_cleared, self.complete_timeout_s, self.poll_interval_s
        ):
            log.warning(
                "slice %s: peers still staged after %.0fs; leaving commit "
                "marker for the next round to clear",
                self.topo.slice_id, self.complete_timeout_s,
            )
            return
        if fenced["hit"]:
            log.warning(
                "slice %s: fencing generation advanced past this round "
                "(gen=%d); leader stops completing the aborted barrier",
                self.topo.slice_id, self.generation,
            )
        try:
            self.api.patch_node_labels(self.node_name, {
                SLICE_COMMIT_LABEL: None,
                SLICE_COMMIT_GEN_LABEL: None,
            })
        except KubeApiError as e:
            log.warning("slice barrier: could not clear commit marker: %s", e)
