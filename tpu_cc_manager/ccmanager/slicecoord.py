"""Slice-wide multi-host commit coordination.

The reference's PPCIe mode is fabric-atomic within one OS image: stage ALL
devices, then reset ALL together "so the NVLink fabric is configured
consistently" (reference main.py:362-368). A multi-host TPU slice spreads
that fabric across machines, so the stage-all/reset-all invariant needs a
cross-host barrier: **no host of an ICI slice may reset its runtime before
every host of the slice is staged and drained.**

The barrier runs over node labels — the same medium the rest of the control
plane uses for desired/actual state — so a crash at any point leaves labels
describing reality (SURVEY.md §7(c)):

- ``cloud.google.com/tpu-cc.slice.staged`` — published by each host after it
  has drained its components and staged the new mode on its chips. Value is
  the staged mode. Cleared when the host finishes (or aborts) the
  transition, so a lingering marker means "host is mid-transition".
- ``cloud.google.com/tpu-cc.slice.commit`` — published by the slice leader
  (``host_index == 0``) on its own node once it observes every host of the
  slice staged. Followers reset only after BOTH observing all hosts staged
  AND seeing the leader's commit marker. The leader clears the marker after
  the barrier completes (best-effort; a stale marker alone can never trigger
  a reset because followers always re-check full staging themselves).

Peer discovery uses the slice-membership label
(:data:`~tpu_cc_manager.labels.SLICE_ID_LABEL`): each host publishes it at
barrier entry, and the barrier is complete when ``num_hosts`` nodes carry the
slice id with a matching staged marker. ``num_hosts`` comes from the device
topology, so a half-visible slice can never commit.

Failure semantics:

- Barrier timeout → :class:`BarrierTimeout` (a :class:`TpuError`): the
  reconcile fails, the host clears its own staged marker (it is about to
  re-admit components, so "staged and drained" is no longer true) and labels
  itself ``failed``. No hardware was touched.
- Crash mid-barrier → the staged marker stays behind; peers time out and
  fail soft. When the crashed host's agent restarts, the apply re-runs and
  re-publishes the marker (idempotent), and the barrier converges.
- Leader crash after publishing commit → followers that saw the marker
  reset (the fabric transition was already decided); the restarted leader's
  re-apply clears its stale marker at barrier entry and re-runs the
  protocol against its peers' already-committed state.
"""

from __future__ import annotations

import logging

from tpu_cc_manager.kubeclient.api import (
    KubeApi,
    KubeApiError,
    caller_retry_attempts,
    classify_kube_error,
    node_labels,
)
from tpu_cc_manager.labels import (
    CC_MODE_STATE_LABEL,
    SLICE_ID_LABEL,
    label_safe,
)
from tpu_cc_manager.obs import trace as obs_trace
from tpu_cc_manager.tpudev.contract import SliceTopology, TpuError
from tpu_cc_manager.utils import retry as retry_mod

log = logging.getLogger(__name__)

SLICE_STAGED_LABEL = "cloud.google.com/tpu-cc.slice.staged"
SLICE_COMMIT_LABEL = "cloud.google.com/tpu-cc.slice.commit"

DEFAULT_BARRIER_TIMEOUT_S = 300.0
# How long the leader lingers after its own transition for peers to clear
# their staged markers before it retires the commit marker.
DEFAULT_COMPLETE_TIMEOUT_S = 60.0


class BarrierTimeout(TpuError):
    """The slice barrier did not form (or complete) in time."""


class SliceBarrier:
    """One host's participation in one slice-wide commit round."""

    def __init__(
        self,
        api: KubeApi,
        node_name: str,
        topo: SliceTopology,
        timeout_s: float = DEFAULT_BARRIER_TIMEOUT_S,
        poll_interval_s: float = 1.0,
        complete_timeout_s: float = DEFAULT_COMPLETE_TIMEOUT_S,
    ) -> None:
        self.api = api
        self.node_name = node_name
        self.topo = topo
        self.timeout_s = timeout_s
        self.poll_interval_s = poll_interval_s
        self.complete_timeout_s = complete_timeout_s
        self.slice_label_value = label_safe(topo.slice_id)
        # Transient-failure policy for the peer listing: short ladder (the
        # outer barrier deadline is authoritative) through the shared
        # jittered backoff instead of the old warn-and-poll-again. One
        # attempt when the client already retries internally (RestKube) —
        # exactly one ladder per logical call.
        self.retry_policy = retry_mod.RetryPolicy(
            max_attempts=caller_retry_attempts(api),
            base_delay_s=min(1.0, max(0.01, poll_interval_s)),
            max_delay_s=max(1.0, poll_interval_s * 4),
        )

    @property
    def is_leader(self) -> bool:
        return self.topo.host_index == 0

    # ------------------------------------------------------------------

    def publish_staged(self, mode: str) -> None:
        """Advertise "this host is drained and staged for ``mode``".

        Also publishes slice membership (peer discovery does not depend on a
        previous successful reconcile) and clears any commit marker this
        node owns from an earlier, possibly crashed, round.
        """
        self.api.patch_node_labels(
            self.node_name,
            {
                SLICE_ID_LABEL: self.slice_label_value,
                SLICE_STAGED_LABEL: mode,
                SLICE_COMMIT_LABEL: None,
            },
        )
        log.info(
            "slice %s host %d/%d: staged marker published (mode=%s)",
            self.topo.slice_id, self.topo.host_index, self.topo.num_hosts, mode,
        )

    def _slice_nodes(self) -> list[dict]:
        return self.retry_policy.call(
            lambda: self.api.list_nodes(
                f"{SLICE_ID_LABEL}={self.slice_label_value}"
            ),
            op="barrier.list_peers",
            classify=classify_kube_error,
        )

    def await_commit(self, mode: str) -> None:
        """Block until this host may reset.

        A peer counts as *ready* when it is staged for ``mode`` — or when
        its actual-state label already reports ``mode``, i.e. it committed
        in an earlier round and this host is a recovering straggler (a crash
        mid-barrier must not wedge the slice: the survivors completed and
        cleared their staged markers, so staging alone could never re-form).

        Every host requires all ``num_hosts`` peers ready. The leader then
        publishes the commit marker and proceeds; followers additionally
        wait for a commit marker — the serialization point that stops a
        follower from resetting while a peer that briefly staged is already
        timing out and re-admitting its components. A follower whose peers
        have ALL already committed proceeds without a marker (the fabric
        transition was decided in the round it missed).
        """
        with obs_trace.span(
            "barrier.await_commit",
            slice=self.topo.slice_id,
            host_index=self.topo.host_index,
            num_hosts=self.topo.num_hosts,
            leader=self.is_leader,
        ):
            self._await_commit(mode)

    def _await_commit(self, mode: str) -> None:
        # Closure state across polls: the commit marker may be observed on
        # an earlier poll than the one where all hosts read ready, and the
        # timeout message reports the last observed readiness.
        state = {"committed_seen": False, "ready": None}

        def barrier_formed() -> bool:
            try:
                nodes = self._slice_nodes()
            except KubeApiError as e:
                # The retry policy already burned its short ladder; keep
                # polling — the barrier deadline is authoritative.
                log.warning("slice barrier: peer listing failed (%s); retrying", e)
                return False
            ready, peers_committed = [], []
            for n in nodes:
                labels = node_labels(n)
                name = n["metadata"]["name"]
                already = labels.get(CC_MODE_STATE_LABEL) == mode
                if labels.get(SLICE_STAGED_LABEL) == mode or already:
                    ready.append(name)
                if already and name != self.node_name:
                    peers_committed.append(name)
            state["ready"] = ready
            state["committed_seen"] = state["committed_seen"] or any(
                node_labels(n).get(SLICE_COMMIT_LABEL) == mode for n in nodes
            )
            all_ready = len(ready) >= self.topo.num_hosts
            if all_ready and self.is_leader:
                self.api.patch_node_labels(
                    self.node_name, {SLICE_COMMIT_LABEL: mode}
                )
                log.info(
                    "slice %s: all %d host(s) ready; leader committing mode=%s",
                    self.topo.slice_id, self.topo.num_hosts, mode,
                )
                return True
            if all_ready and (
                state["committed_seen"]
                or len(peers_committed) >= self.topo.num_hosts - 1
            ):
                log.info(
                    "slice %s host %d: all ready (%s); committing mode=%s",
                    self.topo.slice_id, self.topo.host_index,
                    "leader marker" if state["committed_seen"]
                    else "peers already committed",
                    mode,
                )
                return True
            log.debug(
                "slice %s barrier: %d/%d ready, commit=%s",
                self.topo.slice_id, len(ready), self.topo.num_hosts,
                state["committed_seen"],
            )
            return False

        if not retry_mod.poll_until(
            barrier_formed, self.timeout_s, self.poll_interval_s
        ):
            ready = state["ready"]
            raise BarrierTimeout(
                f"slice {self.topo.slice_id}: barrier for mode {mode} did "
                f"not form within {self.timeout_s:.0f}s "
                f"({len(ready) if ready is not None else '?'}"
                f"/{self.topo.num_hosts} hosts ready)"
            )

    def clear_staged(self) -> None:
        """Withdraw this host's staged marker (it is either done or about
        to re-admit components — either way no longer "staged and
        drained"). Best-effort."""
        try:
            self.api.patch_node_labels(self.node_name, {SLICE_STAGED_LABEL: None})
        except KubeApiError as e:
            log.warning("slice barrier: could not clear staged marker: %s", e)

    def abort(self) -> None:
        self.clear_staged()

    def complete(self, mode: str) -> None:
        """Retire the barrier. The caller runs this AFTER re-admitting
        components (manager.set_cc_mode), so the leader's bounded wait for
        peers never extends the drain window — it only delays the leader's
        own next watch iteration.

        The leader waits for every peer's staged marker to clear before
        retiring the commit marker: clearing it too early would strand
        followers still polling for it. A leftover marker is harmless —
        followers never act on a commit marker without re-verifying full
        staging — and is cleared at the next barrier entry.
        """
        self.clear_staged()  # idempotent; normally already cleared
        if not self.is_leader:
            return
        with obs_trace.span(
            "barrier.complete", slice=self.topo.slice_id, leader=True
        ):
            self._complete_as_leader(mode)

    def _complete_as_leader(self, mode: str) -> None:
        def peers_cleared() -> bool:
            try:
                nodes = self._slice_nodes()
            except KubeApiError:
                return False
            return not any(
                node_labels(n).get(SLICE_STAGED_LABEL) == mode for n in nodes
            )

        if not retry_mod.poll_until(
            peers_cleared, self.complete_timeout_s, self.poll_interval_s
        ):
            log.warning(
                "slice %s: peers still staged after %.0fs; leaving commit "
                "marker for the next round to clear",
                self.topo.slice_id, self.complete_timeout_s,
            )
            return
        try:
            self.api.patch_node_labels(self.node_name, {SLICE_COMMIT_LABEL: None})
        except KubeApiError as e:
            log.warning("slice barrier: could not clear commit marker: %s", e)
