"""Watch-driven informer cache: pool state at O(changes) apiserver cost.

Every pool-shaped decision used to re-list the pool: the rolling
orchestrator listed all nodes at every await poll and window boundary,
pool attestation listed per verification, the slice barrier listed its
peers once per second while waiting. Each of those listings is O(pool)
apiserver work and O(pool) response bytes — fine at 8 nodes, ruinous at
10k (ROADMAP open item #1). The per-node agent already had the answer in
miniature: its watch loop (manager.py) tracks a resourceVersion, rides
bookmarks, resyncs on 410 Gone and reconnects on a jittered ladder — but
only for its OWN node. :class:`NodeInformer` generalizes exactly that
machinery to a label selector:

- **one chunked list** (``limit``/``continue`` pagination, so a 10k-node
  pool arrives in bounded pages) establishes the cache and the
  resourceVersion to watch from;
- **one watch stream** per selector (``KubeApi.watch_nodes_pool``) keeps
  it fresh: ADDED/MODIFIED upsert, DELETED drops (a real apiserver
  delivers "stopped matching the selector" as DELETED — the cache must
  not serve a node that left the pool), BOOKMARK advances the
  resourceVersion on quiet pools so reconnects never 410-expire;
- **410 Gone** (immediate, or as an ERROR event) triggers a full relist —
  the same resync the agent's loop performs;
- transport errors reconnect on the shared jittered backoff ladder
  (utils/retry.py), capped, never giving up: a cache that silently died
  would be worse than no cache, so the thread runs until :meth:`stop`.

Consumers read the **thread-safe local index** — by node name and by
slice label — and block on :meth:`wait` for event-driven wakeups instead
of polling listings: an await loop wakes when the cache changes, checks
its predicate against local state, and costs the apiserver nothing.

Consistency contract (locked in by tests/test_informer.py): after the
stream has caught up, the cache equals a fresh ``list_nodes`` of the same
selector — under any seeded FaultPlan schedule of hangups, stale-rv 410s
and blackouts. Node dicts handed out by :meth:`list`/:meth:`get` are the
cache's own snapshots and MUST be treated as read-only (copying 10k nodes
per read would reintroduce the O(pool) cost client-side).
"""

from __future__ import annotations

import logging
import threading
import time

from tpu_cc_manager.kubeclient.api import (
    KubeApi,
    KubeApiError,
    list_nodes_chunked,
    node_labels,
    resource_version,
)
from tpu_cc_manager.labels import SLICE_ID_LABEL
from tpu_cc_manager.utils import retry as retry_mod

log = logging.getLogger(__name__)

DEFAULT_PAGE_LIMIT = 500
DEFAULT_WATCH_TIMEOUT_S = 300


class NodeInformer:
    """One list+watch per selector, with a thread-safe local index.

    ``version`` increments on every cache mutation; :meth:`wait` blocks
    until it moves past a caller-observed value (or a timeout), which is
    what turns polling loops into event-driven ones.
    """

    def __init__(
        self,
        api: KubeApi,
        selector: str | None = None,
        page_limit: int = DEFAULT_PAGE_LIMIT,
        watch_timeout_s: int = DEFAULT_WATCH_TIMEOUT_S,
        reconnect_delay_s: float = 1.0,
        reconnect_max_delay_s: float = 30.0,
        name: str | None = None,
    ) -> None:
        self.api = api
        self.selector = selector
        self.page_limit = page_limit
        self.watch_timeout_s = watch_timeout_s
        self.name = name or f"informer[{selector or '*'}]"
        self._reconnect_policy = retry_mod.RetryPolicy(
            base_delay_s=max(0.001, reconnect_delay_s),
            max_delay_s=max(reconnect_delay_s, reconnect_max_delay_s),
        )
        self._cond = threading.Condition()
        self._nodes: dict[str, dict] = {}  # cclint: guarded-by(_cond)
        self._by_slice: dict[str, set[str]] = {}  # cclint: guarded-by(_cond)
        self._slice_of: dict[str, str] = {}  # cclint: guarded-by(_cond)
        self._rv: str = ""  # cclint: guarded-by(_cond)
        self._version = 0  # cclint: guarded-by(_cond)
        self._synced = threading.Event()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # Observability counters (tests and the scale bench read these).
        self.relists = 0
        self.events_seen = 0

    # ------------------------------------------------------------------
    # lifecycle

    def start(self, sync_timeout_s: float = 30.0) -> "NodeInformer":
        """Spawn the list+watch thread and block until the first listing
        populated the cache (or ``sync_timeout_s`` passes — callers that
        can make progress unsynced may pass 0)."""
        if self._thread is not None:
            return self
        # Capability probe, synchronous on purpose: the KubeApi default
        # for watch_nodes_pool raises its unsupported marker immediately
        # (it is not a generator), while real implementations hand back a
        # lazy stream with no side effects. Without this, a minimal
        # client's informer would sync once off the listing and then
        # silently serve stale state forever — worse than no cache.
        stream = self.api.watch_nodes_pool(
            self.selector, None, self.watch_timeout_s
        )
        close = getattr(stream, "close", None)
        if close is not None:
            close()
        self._thread = threading.Thread(
            target=self._run, name=self.name, daemon=True
        )
        self._thread.start()
        if sync_timeout_s:
            self.wait_for_sync(sync_timeout_s)
        return self

    def wait_for_sync(self, timeout_s: float = 30.0) -> bool:
        return self._synced.wait(timeout_s)

    def stop(self, join_timeout_s: float = 2.0) -> None:
        self._stop.set()
        with self._cond:
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=join_timeout_s)
            self._thread = None

    def __enter__(self) -> "NodeInformer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------------------------
    # reads (thread-safe; returned dicts are read-only snapshots)

    @property
    def synced(self) -> bool:
        return self._synced.is_set()

    @property
    def version(self) -> int:
        with self._cond:
            return self._version

    def list(self) -> list[dict]:
        """Every cached node of the selector, name-sorted (deterministic
        like a listing)."""
        with self._cond:
            return [self._nodes[n] for n in sorted(self._nodes)]

    def get(self, name: str) -> dict | None:
        with self._cond:
            return self._nodes.get(name)

    def names(self) -> set[str]:
        with self._cond:
            return set(self._nodes)

    def slice_members(self, slice_value: str) -> list[dict]:
        """Cached nodes carrying ``SLICE_ID_LABEL == slice_value`` — the
        slice barrier's peer listing, served locally."""
        with self._cond:
            return [
                self._nodes[n]
                for n in sorted(self._by_slice.get(slice_value, ()))
                if n in self._nodes
            ]

    def wait(self, version: int, timeout_s: float) -> int:
        """Block until the cache moved past ``version`` (or the timeout);
        returns the current version either way. The event-driven
        replacement for a poll sleep: wake on change, re-check, repeat."""
        deadline = time.monotonic() + max(0.0, timeout_s)
        with self._cond:
            while self._version <= version and not self._stop.is_set():
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(timeout=remaining)
            return self._version

    def wait_for(self, predicate, timeout_s: float,
                 recheck_interval_s: float = 1.0) -> bool:
        """Deadline-bounded wait for ``predicate(self)``: evaluated now,
        then after every cache change (and at least every
        ``recheck_interval_s``, so a predicate depending on wall time
        still fires on a quiet pool)."""
        deadline = time.monotonic() + max(0.0, timeout_s)
        version = -1
        while True:
            if predicate(self):
                return True
            remaining = deadline - time.monotonic()
            if remaining <= 0 or self._stop.is_set():
                return False
            version = self.wait(
                version if version >= 0 else self.version,
                min(remaining, recheck_interval_s),
            )

    # ------------------------------------------------------------------
    # the list+watch loop

    def _run(self) -> None:
        consecutive_errors = 0
        while not self._stop.is_set():
            try:
                with self._cond:
                    rv = self._rv
                if not self._synced.is_set() or not rv:
                    self._relist()
                    with self._cond:
                        rv = self._rv
                for event in self.api.watch_nodes_pool(
                    self.selector, rv or None, self.watch_timeout_s
                ):
                    if self._stop.is_set():
                        return
                    if event.type == "ERROR":
                        code = (event.object or {}).get("code")
                        if code == 410:
                            raise KubeApiError(410, "watch ERROR event: Gone")
                        raise KubeApiError(
                            None, f"watch ERROR event: {event.object}"
                        )
                    consecutive_errors = 0
                    self.events_seen += 1
                    erv = resource_version(event.object)
                    if event.type == "BOOKMARK":
                        # Bookmarks carry only metadata.resourceVersion:
                        # track it (that is their whole point) and move on
                        # — upserting would wipe the node's labels.
                        if erv:
                            with self._cond:
                                self._rv = erv
                        continue
                    self._apply(event.type, event.object, erv)
                # Stream ended normally (server-side timeout): reconnect
                # from the tracked rv.
            except Exception as e:
                if self._stop.is_set():
                    return
                consecutive_errors += 1
                if isinstance(e, KubeApiError) and e.status == 410:
                    log.info(
                        "%s: resourceVersion expired; relisting", self.name
                    )
                    # Force a relist on the next loop pass; the relist
                    # itself may fail transiently and rides the ladder.
                    with self._cond:
                        self._rv = ""
                    if consecutive_errors > 1:
                        # A LONE 410 relists immediately (the normal
                        # compaction resync). Back-to-back 410s mean the
                        # relist→watch cycle itself keeps expiring (e.g.
                        # a chunked listing slower than the watch-cache
                        # window): without a throttle that loop is an
                        # unsleeping full-relist hammer — the exact
                        # O(pool) load the cache exists to remove.
                        if self._stop.wait(self._reconnect_policy.delay_for(
                            min(consecutive_errors - 2, 16)
                        )):
                            return
                    continue
                if not isinstance(e, KubeApiError):
                    # A shape bug in an event, a non-numeric per-object rv
                    # in _relist's fallback — anything unexpected. Letting
                    # it kill the thread would freeze the cache with
                    # ``synced`` still true (the exact silent death the
                    # module docstring forbids), so: log loudly, distrust
                    # any half-applied state, and relist from scratch on
                    # the next pass.
                    log.exception(
                        "%s: unexpected error in informer loop (%d "
                        "consecutive); forcing relist", self.name,
                        consecutive_errors,
                    )
                    with self._cond:
                        self._rv = ""
                delay = self._reconnect_policy.delay_for(
                    min(max(0, consecutive_errors - 1), 16)
                )
                log.warning(
                    "%s: watch error (%d consecutive): %s — reconnecting "
                    "in %.2fs", self.name, consecutive_errors, e, delay,
                )
                if self._stop.wait(delay):
                    return

    def _relist(self) -> None:
        items, rv = list_nodes_chunked(
            self.api, self.selector, limit=self.page_limit
        )
        self.relists += 1
        with self._cond:
            self._nodes = {n["metadata"]["name"]: n for n in items}
            self._rebuild_slice_index()
            # A fake/minimal client's listing may carry no rv; fall back
            # to the highest per-object rv so the follow-up watch resumes
            # from the listed state instead of replaying history.
            if not rv:
                rv = str(
                    max(
                        (
                            int(resource_version(n) or 0)
                            for n in items
                        ),
                        default=0,
                    )
                    or ""
                )
            self._rv = rv
            self._version += 1
            self._cond.notify_all()
        self._synced.set()
        log.info(
            "%s: listed %d node(s) at rv=%s", self.name, len(items), rv
        )

    def _apply(self, etype: str, node: dict, rv: str) -> None:
        name = (node.get("metadata") or {}).get("name")
        if not name:
            return
        with self._cond:
            if etype == "DELETED":
                self._nodes.pop(name, None)
            else:
                self._nodes[name] = node
            self._rebuild_slice_entry(name, node, deleted=etype == "DELETED")
            if rv:
                self._rv = rv
            self._version += 1
            self._cond.notify_all()

    def _rebuild_slice_index(self) -> None:  # cclint: requires(_cond)
        self._by_slice = {}
        self._slice_of = {}
        for name, node in self._nodes.items():
            sid = node_labels(node).get(SLICE_ID_LABEL)
            if sid:
                self._slice_of[name] = sid
                self._by_slice.setdefault(sid, set()).add(name)

    def _rebuild_slice_entry(self, name: str, node: dict, deleted: bool) -> None:  # cclint: requires(_cond)
        # O(1) per event via the reverse map — a 10k-node pool must not
        # pay an O(slices) scan per watch event.
        old = self._slice_of.pop(name, None)
        if old is not None:
            members = self._by_slice.get(old)
            if members is not None:
                members.discard(name)
                if not members:
                    del self._by_slice[old]
        if not deleted:
            sid = node_labels(node).get(SLICE_ID_LABEL)
            if sid:
                self._slice_of[name] = sid
                self._by_slice.setdefault(sid, set()).add(name)
