"""Cross-slice attestation for multi-slice data parallelism over DCN.

BASELINE.json configs[4] ("2×v5p-64: CC attestation + Llama-3-8B DP over
DCN"); SURVEY.md §7.9 hard part #3: "cross-slice attestation + re-forming
the DCN mesh after a slice bounces". No reference counterpart.

Protocol (control-plane side — the label/annotation transport mirrors how
the reference carries all its state on node objects):

1. After a slice's CC transition verifies locally, its node agent publishes
   (a) the quote *digest* and mode as node labels — the cheap operator-
   visible summary — and (b) the FULL signed quote (platform JWT/HMAC,
   measurements, nonce) as a node annotation (``publish_quote``).
2. Before a training job re-forms its DCN mesh, it (or the rolling
   orchestrator) calls ``verify_pool_attestation``: every slice in the pool
   must present (a) the expected mode, (b) a fresh-enough quote, (c) the
   SAME runtime digest, and (d) a published quote whose PLATFORM SIGNATURE
   verifies and matches the claimed digest. (c) alone would trust whatever
   a label claims — any principal that can patch node labels could claim
   any digest; (d) is the reference's read-truth-back principle
   (/root/reference/main.py:524-528) applied across slices: the evidence is
   re-verified by the consumer, not trusted from state. A node claiming
   the right digest without a validly signed quote fails pool
   verification.
3. The data-plane side then runs
   :func:`tpu_cc_manager.parallel.distributed.verify_dcn_mesh` for the
   collective-path health check before the first real step.

Trust model of (d): the peer re-checks the platform signature (RS256
against Google's JWKS for tpuvm; fail-closed), the nonce binding inside
the signed token, token expiry, and digest/mode consistency between the
signed measurements and the advertised labels.

**Verifier-challenge freshness (VERDICT weak #5).** Signature checks
alone cannot give peer-chosen-challenge freshness: the nonce was chosen
by the attesting host's own agent, so replay protection within the
token's validity window used to rest entirely on the token's ``exp``.
The challenge protocol closes that: a verifier publishes a fresh nonce
in the :data:`CHALLENGE_ANNOTATION` node annotation
(:func:`issue_pool_challenges`), the node's agent re-quotes BOUND to that
nonce and republishes (ccmanager/manager.py answers challenges from its
watch loop), and pool verification then requires the published quote to
carry the outstanding challenge nonce — a replayed quote that sails
through every signature check fails the challenged path, because its
nonce predates the challenge. Nodes with no outstanding challenge still
verify on the exp-only policy, with the downgrade logged loudly
(``tpu-cc-ctl attest --challenge`` runs the full
challenge→await→verify round).
"""

from __future__ import annotations

import json
import logging
import time

from tpu_cc_manager.kubeclient.api import (
    KubeApi,
    KubeApiError,
    caller_retry_attempts,
    classify_kube_error,
    node_annotations,
    node_labels,
)
from tpu_cc_manager.obs import trace as obs_trace
from tpu_cc_manager.utils import retry as retry_mod
from tpu_cc_manager.tpudev.attestation import (
    AttestationError,
    deserialize_quote,
    quote_digest,
    quote_problems,
    serialize_quote,
)
from tpu_cc_manager.tpudev.contract import AttestationQuote

log = logging.getLogger(__name__)

from tpu_cc_manager import labels as labels_mod
from tpu_cc_manager.labels import (  # noqa: E402 - shared constants
    QUARANTINED_LABEL,
    SLICE_ID_LABEL,
    label_safe,
)

# Wire names centralized in labels.py (cclint surface contract).
QUOTE_ANNOTATION = labels_mod.QUOTE_ANNOTATION
# The full signed quote rides in a real annotation (values up to 256 KiB;
# label values cap at 63 chars): peers re-verify its signature instead of
# trusting the digest labels above.
QUOTE_FULL_ANNOTATION = labels_mod.QUOTE_FULL_ANNOTATION
# Verifier-published nonce challenge (JSON {"nonce": ..., "ts": ...}):
# the agent re-quotes bound to this nonce, giving pool verification
# peer-chosen-challenge freshness instead of exp-only replay protection.
CHALLENGE_ANNOTATION = labels_mod.CHALLENGE_ANNOTATION


class PoolAttestationError(Exception):
    """The pool's slices do not present coherent attestation evidence."""


def quote_label_patch(quote: AttestationQuote | None) -> dict:
    """Label entries advertising a quote — or None-clears when there is no
    quote (mode off), so pool verification can't read stale evidence.

    Returned as a plain dict so callers can fold it into a single node
    merge-patch together with other coordination labels."""
    if quote is None:
        return {
            f"{QUOTE_ANNOTATION}.digest": None,
            f"{QUOTE_ANNOTATION}.mode": None,
            f"{QUOTE_ANNOTATION}.ts": None,
        }
    # Label values are constrained (63 chars, alphanum/-/_/.); pack the
    # payload into multiple labels instead of one JSON blob.
    return {
        f"{QUOTE_ANNOTATION}.digest": quote_digest(quote),
        f"{QUOTE_ANNOTATION}.mode": quote.mode,
        f"{QUOTE_ANNOTATION}.ts": str(int(time.time())),
    }


def publish_quote_annotation(
    api: KubeApi, node_name: str, quote: AttestationQuote | None,
    strict: bool = False,
) -> None:
    """Publish (or clear, for ``quote=None``) the full signed quote in the
    node annotation peers verify. By default best-effort on clients
    without annotation support (the digest labels still work there; the
    pool verifier just reports those nodes as signature-unverifiable);
    ``strict`` re-raises instead — the challenge-answer path needs the
    failure, because swallowing it would let the caller mark an answer
    delivered that the apiserver never saw."""
    value = serialize_quote(quote) if quote is not None else None
    try:
        api.patch_node_annotations(node_name, {QUOTE_FULL_ANNOTATION: value})
    except KubeApiError as e:
        if strict:
            raise
        log.warning(
            "could not publish signed quote annotation on %s: %s",
            node_name, e,
        )


def retire_answered_challenge(api: KubeApi, node_name: str, nonce: str) -> None:
    """Clear the challenge annotation IF it still holds the nonce the
    agent just answered. The condition matters: a newer challenge issued
    while the agent was fetching its quote (a device round trip takes
    seconds) must not be erased unseen — an unconditional clear would
    leave the new verifier's await timing out on a node that never got
    the chance to answer. Best-effort: a lingering ANSWERED challenge is
    harmless (the published quote is bound to it, so verification still
    passes); the clear only keeps a one-time challenge from re-arming
    after the next reconcile republishes a self-nonce quote."""
    try:
        current = challenge_nonce_of(api.get_node(node_name))
        if current == nonce:
            api.patch_node_annotations(node_name, {CHALLENGE_ANNOTATION: None})
    except KubeApiError as e:
        log.warning(
            "could not retire answered challenge on %s: %s", node_name, e
        )


def publish_quote(
    api: KubeApi, node_name: str, quote: AttestationQuote,
    strict: bool = False,
) -> dict:
    """Publish a quote on the node: digest+mode as labels (the operator-
    visible summary) and the full signed quote as an annotation (what
    peers actually verify)."""
    patch = quote_label_patch(quote)
    api.patch_node_labels(node_name, patch)
    publish_quote_annotation(api, node_name, quote, strict=strict)
    payload = {
        "slice": quote.slice_id,
        "mode": quote.mode,
        "digest": patch[f"{QUOTE_ANNOTATION}.digest"],
        "ts": int(patch[f"{QUOTE_ANNOTATION}.ts"]),
    }
    log.info("published attestation for %s: %s", node_name, payload)
    return payload


def challenge_nonce_of(node: dict) -> str | None:
    """The outstanding verifier-challenge nonce on a node (None when no
    challenge was issued or the annotation is unreadable — an unreadable
    challenge degrades to the exp-only policy rather than crashing the
    agent that merely wants to answer it)."""
    raw = node_annotations(node).get(CHALLENGE_ANNOTATION)
    if not raw:
        return None
    try:
        nonce = json.loads(raw).get("nonce")
        return str(nonce) if nonce else None
    except (ValueError, AttributeError):
        log.warning("unreadable challenge annotation: %r", raw[:120])
        return None


def issue_pool_challenges(
    api: KubeApi, selector: str, informer=None
) -> dict[str, str]:
    """Publish a FRESH per-node nonce challenge on every healthy matching
    node; returns {node_name: nonce}. Per-node nonces (not one pool-wide
    value) so one node's answer can never satisfy another node's
    challenge. Quarantined hosts are skipped — their evidence is excluded
    from verification anyway. Best-effort on clients without annotation
    support: returns {} and verification stays on the exp-only policy.
    ``informer`` (ccmanager/informer.py, same selector) serves the
    membership read from the watch-driven cache — the writes still go to
    the apiserver, but the O(pool) listing per challenge round is gone."""
    from tpu_cc_manager.tpudev import attestation as attestation_mod

    challenges: dict[str, str] = {}
    # ``synced`` gate (here and below): an informer whose first listing
    # hasn't landed reports an EMPTY pool, not an error — fall back to a
    # real listing rather than silently challenging/collecting nothing.
    for node in (informer.list() if informer is not None and informer.synced
                 else api.list_nodes(selector)):
        name = node["metadata"]["name"]
        if node_labels(node).get(QUARANTINED_LABEL) == "true":
            continue
        nonce = attestation_mod.fresh_nonce()
        try:
            api.patch_node_annotations(name, {
                CHALLENGE_ANNOTATION: json.dumps(
                    {"nonce": nonce, "ts": int(time.time())},
                    sort_keys=True, separators=(",", ":"),
                )
            })
        except KubeApiError as e:
            if e.status is None and "not supported" in (e.reason or ""):
                # Structural: this CLIENT cannot publish annotations at
                # all (the KubeApi capability default). Challenged
                # attestation is impossible here — degrade to the
                # documented exp-only fallback instead of failing every
                # healthy node on challenges they could never receive.
                log.warning(
                    "client cannot publish challenge annotations (%s); "
                    "falling back to exp-only verification", e,
                )
                return {}
            # Transient per-node flake: the node stays IN the challenge
            # set even though it never saw the challenge — it will fail
            # challenged verification loudly. Dropping it instead would
            # verify it exp-only, a silent downgrade of exactly the node
            # the flake made unattestable, in the mode whose purpose is
            # defeating replay.
            log.warning(
                "could not publish challenge on %s (%s); the node WILL "
                "fail challenged verification", name, e,
            )
        challenges[name] = nonce
    log.info(
        "issued attestation challenges to %d node(s)", len(challenges)
    )
    return challenges


def await_challenge_answers(
    api: KubeApi,
    selector: str,
    challenges: dict[str, str],
    timeout_s: float = 30.0,
    poll_interval_s: float = 1.0,
    informer=None,
) -> list[str]:
    """Wait (bounded) until every challenged node republished a quote
    bound to its challenge nonce; returns the node names still
    unanswered at the deadline (empty = all answered). Lenient like the
    drain handshake: a wedged agent delays verification by at most the
    timeout and then FAILS the challenged check — it cannot veto it."""
    pending = dict(challenges)

    def all_answered() -> bool:
        from tpu_cc_manager.kubeclient.api import classify_kube_error

        try:
            nodes = (
                informer.list()
                if informer is not None and informer.synced
                else api.list_nodes(selector)
            )
        except KubeApiError as e:
            verdict = classify_kube_error(e)
            if verdict is None or not verdict.transient:
                raise
            # One throttle/blip must not abort a 30 s bounded wait whose
            # next tick would likely succeed; the deadline bounds us.
            log.warning("challenge poll listing failed (transient): %s", e)
            return False
        for node in nodes:
            name = node["metadata"]["name"]
            nonce = pending.get(name)
            if nonce is None:
                continue
            raw = node_annotations(node).get(QUOTE_FULL_ANNOTATION)
            if raw is None:
                continue
            try:
                quote = deserialize_quote(raw)
            except AttestationError:
                continue
            if quote.nonce == nonce:
                del pending[name]
        return not pending

    if informer is not None:
        # Event-driven: wake on cache changes (each answer republishes the
        # quote annotation, which is a node MODIFIED event) instead of
        # paying a pool listing per poll tick.
        informer.wait_for(
            lambda _informer: all_answered(), timeout_s,
            recheck_interval_s=poll_interval_s,
        )
    else:
        retry_mod.poll_until(all_answered, timeout_s, poll_interval_s)
    if pending:
        log.warning(
            "challenge unanswered by %s after %.0fs",
            sorted(pending), timeout_s,
        )
    return sorted(pending)


def collect_pool_quotes(
    api: KubeApi, selector: str, informer=None
) -> dict[str, dict]:
    """slice_id -> {digest, mode, ts, nodes, missing} across matching nodes.

    Every host of a slice must attest, so hosts carrying the slice label but
    no quote are recorded in ``missing`` (not silently skipped), modes must
    agree across hosts (else ``mode`` becomes "MIXED"), and ``ts`` is the
    OLDEST host's timestamp so staleness checks see the worst host. With
    an ``informer`` (same selector) the whole collection is a cache read:
    pool attestation stops costing one O(pool) listing per verification."""
    if informer is not None and informer.synced:
        nodes = informer.list()
    else:
        # Transient apiserver failures ride the shared jittered backoff; a
        # pool verification gating a DCN mesh re-form should not fail on
        # one flaky listing. One attempt when the client retries
        # internally (RestKube).
        policy = retry_mod.RetryPolicy(
            max_attempts=caller_retry_attempts(api), base_delay_s=0.5
        )
        nodes = policy.call(
            lambda: api.list_nodes(selector),
            op="pool_attest.list_nodes",
            classify=classify_kube_error,
        )
    slices: dict[str, dict] = {}
    for node in nodes:
        labels = node_labels(node)
        name = node["metadata"]["name"]
        digest = labels.get(f"{QUOTE_ANNOTATION}.digest")
        slice_id = labels.get(SLICE_ID_LABEL) or f"node/{name}"
        entry = slices.setdefault(
            slice_id,
            {"digest": None, "mode": None, "ts": None, "nodes": [],
             "missing": [], "quarantined": [], "quotes": {},
             "node_digests": {}, "challenges": {}},
        )
        entry["challenges"][name] = challenge_nonce_of(node)
        if labels.get(QUARANTINED_LABEL) == "true":
            # A quarantined host is out of the serving pool (remediation
            # ladder): its absent/stale evidence must not fail the healthy
            # hosts' verification — it is reported, not enforced. A slice
            # whose EVERY host is quarantined still fails (no evidence at
            # all reads as a missing slice, which it operationally is).
            entry["quarantined"].append(name)
            log.warning(
                "pool attestation: skipping quarantined host %s "
                "(slice %s)", name, slice_id,
            )
            continue
        if digest is None:
            entry["missing"].append(name)
            continue
        mode = labels.get(f"{QUOTE_ANNOTATION}.mode", "")
        try:
            ts = int(labels.get(f"{QUOTE_ANNOTATION}.ts", "0") or 0)
        except ValueError:
            # A forged/corrupt ts label must degrade to "maximally stale"
            # (epoch 0 → the staleness problem fires), not crash the
            # verifier outside its PoolAttestationError contract.
            ts = 0
        entry["nodes"].append(name)
        entry["digest"] = digest if entry["digest"] in (None, digest) else "MIXED"
        entry["mode"] = mode if entry["mode"] in (None, mode) else "MIXED"
        entry["ts"] = ts if entry["ts"] is None else min(entry["ts"], ts)
        # The full signed quote, when published: None records "labels only"
        # so the verifier can fail signature-required pools loudly.
        raw = node_annotations(node).get(QUOTE_FULL_ANNOTATION)
        quote = None
        if raw is not None:
            try:
                quote = deserialize_quote(raw)
            except AttestationError as e:
                log.warning("unparseable quote annotation on %s: %s", name, e)
        entry["quotes"][name] = quote
        entry["node_digests"][name] = digest
    # Slices where no host attested at all keep digest None.
    return slices


def _peer_verify_node_quote(
    sid: str,
    name: str,
    quote: AttestationQuote | None,
    label_digest: str,
    expected_mode: str,
    allow_fake: bool,
    challenge_nonce: str | None = None,
) -> list[str]:
    """Signature-grade checks for one node's published quote: present,
    platform signature + nonce binding verify, the signed quote names THIS
    node's slice, signed measurements match the advertised digest labels,
    and the runtime was actually measured.

    With ``challenge_nonce`` (a verifier-published challenge outstanding
    on the node) the quote must be bound to THAT nonce: the whole
    quote-problems pass runs against the challenge, so a replayed quote —
    valid signature, matching digest, same slice — fails here, because
    its self-chosen nonce predates the challenge. Without a challenge the
    quote's own nonce is used (exp-only freshness; the caller logs the
    downgrade)."""
    where = f"slice {sid}: node {name}"
    if quote is None:
        return [
            f"{where}: digest label without a verifiable signed quote "
            f"(annotation {QUOTE_FULL_ANNOTATION} missing or unparseable)"
        ]
    challenged = challenge_nonce is not None
    challenge_missed = challenged and quote.nonce != challenge_nonce
    # On a missed challenge, run the structural checks against the
    # quote's own nonce and report the miss ONCE below — passing the
    # challenge nonce into quote_problems too would double-report the
    # same defect ("nonce mismatch" + "not bound to the challenge").
    expected_nonce = (
        challenge_nonce if challenged and not challenge_missed
        else quote.nonce
    )
    problems = [
        f"{where}: {p}"
        for p in quote_problems(
            quote, expected_nonce, expected_mode, allow_fake=allow_fake
        )
    ]
    if challenge_missed:
        problems.append(
            f"{where}: published quote is not bound to the outstanding "
            "verifier challenge (replayed or stale evidence; exp-only "
            "freshness is not accepted once a challenge is issued)"
        )
    # Slice binding: without it, a node could replay ANOTHER slice's whole
    # evidence (labels + annotation verbatim) and pass every signature
    # check — the signed quote must name the slice this node advertises.
    # Skipped for the node/<name> fallback grouping (no slice label to
    # bind against; the label alphabet can't even contain "/").
    if not sid.startswith("node/") and label_safe(quote.slice_id) != sid:
        problems.append(
            f"{where}: signed quote names slice "
            f"{label_safe(quote.slice_id)!r}, node advertises {sid!r} — "
            "replayed evidence from another slice"
        )
    if quote_digest(quote) != label_digest:
        # The label is what digest-equality compares; a signed quote that
        # doesn't hash to it means the label claims a runtime the platform
        # never signed for.
        problems.append(
            f"{where}: advertised digest label does not match the signed "
            "quote's measurements"
        )
    if quote.measurements.get("runtime_files") == "0":
        # Without this, every unmeasured host hashes the same constant and
        # cross-slice digest equality passes vacuously (ADVICE r4 #4).
        problems.append(
            f"{where}: runtime was never measured (runtime_files=0: no "
            "measure glob matched; digest equality would be vacuous)"
        )
    return problems


def verify_pool_attestation(
    api: KubeApi,
    selector: str,
    expected_mode: str,
    expected_slices: int | None = None,
    max_age_s: float | None = 3600.0,
    allow_fake: bool = False,
    verify_signatures: bool = True,
    challenges: dict[str, str] | None = None,
    informer=None,
) -> dict[str, dict]:
    """Check every slice attests the expected mode with one common digest,
    re-verifying each node's published quote SIGNATURE — not just the
    self-published digest labels (which anyone with node-patch RBAC could
    forge).

    ``allow_fake`` admits fake-platform quotes (HMAC, shared test key) and
    must only be set when the pool runs the fake device layer.
    ``verify_signatures=False`` restores the r4 digest-labels-only check
    for clients that cannot read annotations; it downgrades the guarantee
    from platform-signed to RBAC-trust and logs accordingly.
    ``challenges`` ({node: nonce}, from :func:`issue_pool_challenges`) is
    the verifier's AUTHORITATIVE challenge set: quotes on those nodes
    must be bound to those nonces. When None, outstanding challenge
    annotations are read opportunistically from the nodes (weaker: a
    principal with node-patch RBAC could clear an annotation to force
    the exp-only fallback, which is why the fallback is logged).

    Returns the slice map on success; raises PoolAttestationError with the
    full discrepancy list otherwise."""
    with obs_trace.span(
        "pool_attest.verify", selector=selector, expected_mode=expected_mode
    ) as sp:
        slices = _verify_pool_attestation(
            api, selector, expected_mode, expected_slices, max_age_s,
            allow_fake, verify_signatures, challenges, informer,
        )
        sp.set_attribute("slices", len(slices))
        return slices


def _verify_pool_attestation(
    api: KubeApi,
    selector: str,
    expected_mode: str,
    expected_slices: int | None,
    max_age_s: float | None,
    allow_fake: bool,
    verify_signatures: bool,
    challenges: dict[str, str] | None = None,
    informer=None,
) -> dict[str, dict]:
    slices = collect_pool_quotes(api, selector, informer=informer)
    if challenges is not None:
        # The verifier's own challenge set overrides whatever the nodes
        # advertise — an annotation a hostile writer cleared (or never
        # relayed) must not quietly downgrade a challenged verification.
        for entry in slices.values():
            entry["challenges"] = {
                name: challenges.get(name)
                for name in list(entry.get("challenges") or {})
            }
    problems: list[str] = []
    if not any(e["nodes"] for e in slices.values()):
        problems.append("no slice published any attestation")
    if expected_slices is not None and len(slices) != expected_slices:
        problems.append(f"expected {expected_slices} slices, found {len(slices)}")
    if not verify_signatures:
        log.warning(
            "pool attestation running digest-labels-only (signature "
            "verification disabled): label forgery is NOT detected"
        )
    now = time.time()
    digests = set()
    # Nodes verified on exp-only freshness (no outstanding verifier
    # challenge): aggregated into ONE warning after the walk — a per-node
    # warning would emit O(pool) identical lines on every plain attest.
    exp_only_nodes: list[str] = []
    for sid, entry in sorted(slices.items()):
        if entry["missing"]:
            problems.append(
                f"slice {sid}: host(s) without attestation: "
                f"{sorted(entry['missing'])}"
            )
        if entry["quarantined"] and not entry["nodes"] and not entry["missing"]:
            # Quarantined hosts are skipped, but a slice with NO healthy
            # host left has no evidence at all — it must not read as
            # verified just because its failures were contained.
            problems.append(
                f"slice {sid}: every host quarantined "
                f"({sorted(entry['quarantined'])}); no attestable host left"
            )
        if entry["digest"] is None:
            continue  # covered by the missing/quarantined problems above
        if entry["digest"] == "MIXED":
            problems.append(f"slice {sid}: hosts disagree on runtime digest")
        else:
            digests.add(entry["digest"])
        if entry["mode"] == "MIXED":
            problems.append(f"slice {sid}: hosts disagree on attested mode")
        elif entry["mode"] != expected_mode:
            problems.append(
                f"slice {sid}: mode {entry['mode']!r} != expected {expected_mode!r}"
            )
        if max_age_s is not None and now - entry["ts"] > max_age_s:
            problems.append(f"slice {sid}: quote is stale ({int(now - entry['ts'])}s)")
        if verify_signatures:
            for name in sorted(entry["nodes"]):
                challenge = (entry.get("challenges") or {}).get(name)
                if challenge is None:
                    exp_only_nodes.append(f"{sid}/{name}")
                problems.extend(_peer_verify_node_quote(
                    sid, name, entry["quotes"].get(name),
                    entry["node_digests"][name], expected_mode, allow_fake,
                    challenge_nonce=challenge,
                ))
    if exp_only_nodes:
        shown = ", ".join(exp_only_nodes[:6])
        if len(exp_only_nodes) > 6:
            shown += f", … ({len(exp_only_nodes) - 6} more)"
        log.warning(
            "pool attestation: %d node(s) verified with exp-only "
            "freshness (no verifier challenge outstanding: %s) — run "
            "`tpu-cc-ctl attest --challenge` for challenged "
            "re-attestation", len(exp_only_nodes), shown,
        )
    if len(digests) > 1:
        problems.append(
            f"slices report {len(digests)} distinct runtime digests: "
            f"{sorted(digests)}"
        )
    if problems:
        raise PoolAttestationError("; ".join(problems))
    log.info(
        "pool attestation verified: %d slice(s), digest=%s, mode=%s, "
        "signatures=%s",
        len(slices), next(iter(digests)), expected_mode,
        "verified" if verify_signatures else "SKIPPED",
    )
    return slices


def pool_report(api: KubeApi, selector: str, informer=None) -> str:
    """Human-readable attestation table (CLI helper)."""
    slices = collect_pool_quotes(api, selector, informer=informer)
    lines = [
        f"{'SLICE':<28} {'MODE':<10} {'DIGEST':<18} {'ATTESTED':<9} "
        f"{'MISSING':<8} QUAR"
    ]
    for sid, e in sorted(slices.items()):
        lines.append(
            f"{sid:<28} {str(e['mode'] or '-'):<10} "
            f"{str(e['digest'] or '-'):<18} {len(e['nodes']):<9} "
            f"{len(e['missing']):<8} {len(e['quarantined'])}"
        )
    return "\n".join(lines)
