"""Cross-slice attestation for multi-slice data parallelism over DCN.

BASELINE.json configs[4] ("2×v5p-64: CC attestation + Llama-3-8B DP over
DCN"); SURVEY.md §7.9 hard part #3: "cross-slice attestation + re-forming
the DCN mesh after a slice bounces". No reference counterpart.

Protocol (control-plane side — the label/annotation transport mirrors how
the reference carries all its state on node objects):

1. After a slice's CC transition verifies locally, its node agent publishes
   (a) the quote *digest* and mode as node labels — the cheap operator-
   visible summary — and (b) the FULL signed quote (platform JWT/HMAC,
   measurements, nonce) as a node annotation (``publish_quote``).
2. Before a training job re-forms its DCN mesh, it (or the rolling
   orchestrator) calls ``verify_pool_attestation``: every slice in the pool
   must present (a) the expected mode, (b) a fresh-enough quote, (c) the
   SAME runtime digest, and (d) a published quote whose PLATFORM SIGNATURE
   verifies and matches the claimed digest. (c) alone would trust whatever
   a label claims — any principal that can patch node labels could claim
   any digest; (d) is the reference's read-truth-back principle
   (/root/reference/main.py:524-528) applied across slices: the evidence is
   re-verified by the consumer, not trusted from state. A node claiming
   the right digest without a validly signed quote fails pool
   verification.
3. The data-plane side then runs
   :func:`tpu_cc_manager.parallel.distributed.verify_dcn_mesh` for the
   collective-path health check before the first real step.

Trust model of (d): the peer re-checks the platform signature (RS256
against Google's JWKS for tpuvm; fail-closed), the nonce binding inside
the signed token, token expiry, and digest/mode consistency between the
signed measurements and the advertised labels. What it cannot give is
peer-chosen-challenge freshness — the nonce was chosen by the attesting
host's own agent, so replay protection within the token's validity window
rests on the token's ``exp``. A peer-challenge protocol would need an
interactive round per verifier and is deliberately out of scope for a
control-plane gate.
"""

from __future__ import annotations

import logging
import time

from tpu_cc_manager.kubeclient.api import (
    KubeApi,
    KubeApiError,
    caller_retry_attempts,
    classify_kube_error,
    node_annotations,
    node_labels,
)
from tpu_cc_manager.obs import trace as obs_trace
from tpu_cc_manager.utils import retry as retry_mod
from tpu_cc_manager.tpudev.attestation import (
    AttestationError,
    deserialize_quote,
    quote_digest,
    quote_problems,
    serialize_quote,
)
from tpu_cc_manager.tpudev.contract import AttestationQuote

log = logging.getLogger(__name__)

from tpu_cc_manager.labels import (  # noqa: E402 - shared constants
    QUARANTINED_LABEL,
    SLICE_ID_LABEL,
    label_safe,
)

QUOTE_ANNOTATION = "cloud.google.com/tpu-cc.attestation"
# The full signed quote rides in a real annotation (values up to 256 KiB;
# label values cap at 63 chars): peers re-verify its signature instead of
# trusting the digest labels above.
QUOTE_FULL_ANNOTATION = "cloud.google.com/tpu-cc.quote"


class PoolAttestationError(Exception):
    """The pool's slices do not present coherent attestation evidence."""


def quote_label_patch(quote: AttestationQuote | None) -> dict:
    """Label entries advertising a quote — or None-clears when there is no
    quote (mode off), so pool verification can't read stale evidence.

    Returned as a plain dict so callers can fold it into a single node
    merge-patch together with other coordination labels."""
    if quote is None:
        return {
            f"{QUOTE_ANNOTATION}.digest": None,
            f"{QUOTE_ANNOTATION}.mode": None,
            f"{QUOTE_ANNOTATION}.ts": None,
        }
    # Label values are constrained (63 chars, alphanum/-/_/.); pack the
    # payload into multiple labels instead of one JSON blob.
    return {
        f"{QUOTE_ANNOTATION}.digest": quote_digest(quote),
        f"{QUOTE_ANNOTATION}.mode": quote.mode,
        f"{QUOTE_ANNOTATION}.ts": str(int(time.time())),
    }


def publish_quote_annotation(
    api: KubeApi, node_name: str, quote: AttestationQuote | None
) -> None:
    """Publish (or clear, for ``quote=None``) the full signed quote in the
    node annotation peers verify. Best-effort on clients without
    annotation support: the digest labels still work there, the pool
    verifier just reports those nodes as signature-unverifiable."""
    value = serialize_quote(quote) if quote is not None else None
    try:
        api.patch_node_annotations(node_name, {QUOTE_FULL_ANNOTATION: value})
    except KubeApiError as e:
        log.warning(
            "could not publish signed quote annotation on %s: %s",
            node_name, e,
        )


def publish_quote(api: KubeApi, node_name: str, quote: AttestationQuote) -> dict:
    """Publish a quote on the node: digest+mode as labels (the operator-
    visible summary) and the full signed quote as an annotation (what
    peers actually verify)."""
    patch = quote_label_patch(quote)
    api.patch_node_labels(node_name, patch)
    publish_quote_annotation(api, node_name, quote)
    payload = {
        "slice": quote.slice_id,
        "mode": quote.mode,
        "digest": patch[f"{QUOTE_ANNOTATION}.digest"],
        "ts": int(patch[f"{QUOTE_ANNOTATION}.ts"]),
    }
    log.info("published attestation for %s: %s", node_name, payload)
    return payload


def collect_pool_quotes(api: KubeApi, selector: str) -> dict[str, dict]:
    """slice_id -> {digest, mode, ts, nodes, missing} across matching nodes.

    Every host of a slice must attest, so hosts carrying the slice label but
    no quote are recorded in ``missing`` (not silently skipped), modes must
    agree across hosts (else ``mode`` becomes "MIXED"), and ``ts`` is the
    OLDEST host's timestamp so staleness checks see the worst host."""
    # Transient apiserver failures ride the shared jittered backoff; a pool
    # verification gating a DCN mesh re-form should not fail on one flaky
    # listing. One attempt when the client retries internally (RestKube).
    policy = retry_mod.RetryPolicy(
        max_attempts=caller_retry_attempts(api), base_delay_s=0.5
    )
    nodes = policy.call(
        lambda: api.list_nodes(selector),
        op="pool_attest.list_nodes",
        classify=classify_kube_error,
    )
    slices: dict[str, dict] = {}
    for node in nodes:
        labels = node_labels(node)
        name = node["metadata"]["name"]
        digest = labels.get(f"{QUOTE_ANNOTATION}.digest")
        slice_id = labels.get(SLICE_ID_LABEL) or f"node/{name}"
        entry = slices.setdefault(
            slice_id,
            {"digest": None, "mode": None, "ts": None, "nodes": [],
             "missing": [], "quarantined": [], "quotes": {},
             "node_digests": {}},
        )
        if labels.get(QUARANTINED_LABEL) == "true":
            # A quarantined host is out of the serving pool (remediation
            # ladder): its absent/stale evidence must not fail the healthy
            # hosts' verification — it is reported, not enforced. A slice
            # whose EVERY host is quarantined still fails (no evidence at
            # all reads as a missing slice, which it operationally is).
            entry["quarantined"].append(name)
            log.warning(
                "pool attestation: skipping quarantined host %s "
                "(slice %s)", name, slice_id,
            )
            continue
        if digest is None:
            entry["missing"].append(name)
            continue
        mode = labels.get(f"{QUOTE_ANNOTATION}.mode", "")
        try:
            ts = int(labels.get(f"{QUOTE_ANNOTATION}.ts", "0") or 0)
        except ValueError:
            # A forged/corrupt ts label must degrade to "maximally stale"
            # (epoch 0 → the staleness problem fires), not crash the
            # verifier outside its PoolAttestationError contract.
            ts = 0
        entry["nodes"].append(name)
        entry["digest"] = digest if entry["digest"] in (None, digest) else "MIXED"
        entry["mode"] = mode if entry["mode"] in (None, mode) else "MIXED"
        entry["ts"] = ts if entry["ts"] is None else min(entry["ts"], ts)
        # The full signed quote, when published: None records "labels only"
        # so the verifier can fail signature-required pools loudly.
        raw = node_annotations(node).get(QUOTE_FULL_ANNOTATION)
        quote = None
        if raw is not None:
            try:
                quote = deserialize_quote(raw)
            except AttestationError as e:
                log.warning("unparseable quote annotation on %s: %s", name, e)
        entry["quotes"][name] = quote
        entry["node_digests"][name] = digest
    # Slices where no host attested at all keep digest None.
    return slices


def _peer_verify_node_quote(
    sid: str,
    name: str,
    quote: AttestationQuote | None,
    label_digest: str,
    expected_mode: str,
    allow_fake: bool,
) -> list[str]:
    """Signature-grade checks for one node's published quote: present,
    platform signature + nonce binding verify, the signed quote names THIS
    node's slice, signed measurements match the advertised digest labels,
    and the runtime was actually measured."""
    where = f"slice {sid}: node {name}"
    if quote is None:
        return [
            f"{where}: digest label without a verifiable signed quote "
            f"(annotation {QUOTE_FULL_ANNOTATION} missing or unparseable)"
        ]
    problems = [
        f"{where}: {p}"
        for p in quote_problems(
            quote, quote.nonce, expected_mode, allow_fake=allow_fake
        )
    ]
    # Slice binding: without it, a node could replay ANOTHER slice's whole
    # evidence (labels + annotation verbatim) and pass every signature
    # check — the signed quote must name the slice this node advertises.
    # Skipped for the node/<name> fallback grouping (no slice label to
    # bind against; the label alphabet can't even contain "/").
    if not sid.startswith("node/") and label_safe(quote.slice_id) != sid:
        problems.append(
            f"{where}: signed quote names slice "
            f"{label_safe(quote.slice_id)!r}, node advertises {sid!r} — "
            "replayed evidence from another slice"
        )
    if quote_digest(quote) != label_digest:
        # The label is what digest-equality compares; a signed quote that
        # doesn't hash to it means the label claims a runtime the platform
        # never signed for.
        problems.append(
            f"{where}: advertised digest label does not match the signed "
            "quote's measurements"
        )
    if quote.measurements.get("runtime_files") == "0":
        # Without this, every unmeasured host hashes the same constant and
        # cross-slice digest equality passes vacuously (ADVICE r4 #4).
        problems.append(
            f"{where}: runtime was never measured (runtime_files=0: no "
            "measure glob matched; digest equality would be vacuous)"
        )
    return problems


def verify_pool_attestation(
    api: KubeApi,
    selector: str,
    expected_mode: str,
    expected_slices: int | None = None,
    max_age_s: float | None = 3600.0,
    allow_fake: bool = False,
    verify_signatures: bool = True,
) -> dict[str, dict]:
    """Check every slice attests the expected mode with one common digest,
    re-verifying each node's published quote SIGNATURE — not just the
    self-published digest labels (which anyone with node-patch RBAC could
    forge).

    ``allow_fake`` admits fake-platform quotes (HMAC, shared test key) and
    must only be set when the pool runs the fake device layer.
    ``verify_signatures=False`` restores the r4 digest-labels-only check
    for clients that cannot read annotations; it downgrades the guarantee
    from platform-signed to RBAC-trust and logs accordingly.

    Returns the slice map on success; raises PoolAttestationError with the
    full discrepancy list otherwise."""
    with obs_trace.span(
        "pool_attest.verify", selector=selector, expected_mode=expected_mode
    ) as sp:
        slices = _verify_pool_attestation(
            api, selector, expected_mode, expected_slices, max_age_s,
            allow_fake, verify_signatures,
        )
        sp.set_attribute("slices", len(slices))
        return slices


def _verify_pool_attestation(
    api: KubeApi,
    selector: str,
    expected_mode: str,
    expected_slices: int | None,
    max_age_s: float | None,
    allow_fake: bool,
    verify_signatures: bool,
) -> dict[str, dict]:
    slices = collect_pool_quotes(api, selector)
    problems: list[str] = []
    if not any(e["nodes"] for e in slices.values()):
        problems.append("no slice published any attestation")
    if expected_slices is not None and len(slices) != expected_slices:
        problems.append(f"expected {expected_slices} slices, found {len(slices)}")
    if not verify_signatures:
        log.warning(
            "pool attestation running digest-labels-only (signature "
            "verification disabled): label forgery is NOT detected"
        )
    now = time.time()
    digests = set()
    for sid, entry in sorted(slices.items()):
        if entry["missing"]:
            problems.append(
                f"slice {sid}: host(s) without attestation: "
                f"{sorted(entry['missing'])}"
            )
        if entry["quarantined"] and not entry["nodes"] and not entry["missing"]:
            # Quarantined hosts are skipped, but a slice with NO healthy
            # host left has no evidence at all — it must not read as
            # verified just because its failures were contained.
            problems.append(
                f"slice {sid}: every host quarantined "
                f"({sorted(entry['quarantined'])}); no attestable host left"
            )
        if entry["digest"] is None:
            continue  # covered by the missing/quarantined problems above
        if entry["digest"] == "MIXED":
            problems.append(f"slice {sid}: hosts disagree on runtime digest")
        else:
            digests.add(entry["digest"])
        if entry["mode"] == "MIXED":
            problems.append(f"slice {sid}: hosts disagree on attested mode")
        elif entry["mode"] != expected_mode:
            problems.append(
                f"slice {sid}: mode {entry['mode']!r} != expected {expected_mode!r}"
            )
        if max_age_s is not None and now - entry["ts"] > max_age_s:
            problems.append(f"slice {sid}: quote is stale ({int(now - entry['ts'])}s)")
        if verify_signatures:
            for name in sorted(entry["nodes"]):
                problems.extend(_peer_verify_node_quote(
                    sid, name, entry["quotes"].get(name),
                    entry["node_digests"][name], expected_mode, allow_fake,
                ))
    if len(digests) > 1:
        problems.append(
            f"slices report {len(digests)} distinct runtime digests: "
            f"{sorted(digests)}"
        )
    if problems:
        raise PoolAttestationError("; ".join(problems))
    log.info(
        "pool attestation verified: %d slice(s), digest=%s, mode=%s, "
        "signatures=%s",
        len(slices), next(iter(digests)), expected_mode,
        "verified" if verify_signatures else "SKIPPED",
    )
    return slices


def pool_report(api: KubeApi, selector: str) -> str:
    """Human-readable attestation table (CLI helper)."""
    slices = collect_pool_quotes(api, selector)
    lines = [
        f"{'SLICE':<28} {'MODE':<10} {'DIGEST':<18} {'ATTESTED':<9} "
        f"{'MISSING':<8} QUAR"
    ]
    for sid, e in sorted(slices.items()):
        lines.append(
            f"{sid:<28} {str(e['mode'] or '-'):<10} "
            f"{str(e['digest'] or '-'):<18} {len(e['nodes']):<9} "
            f"{len(e['missing']):<8} {len(e['quarantined'])}"
        )
    return "\n".join(lines)
