"""Cross-slice attestation for multi-slice data parallelism over DCN.

BASELINE.json configs[4] ("2×v5p-64: CC attestation + Llama-3-8B DP over
DCN"); SURVEY.md §7.9 hard part #3: "cross-slice attestation + re-forming
the DCN mesh after a slice bounces". No reference counterpart.

Protocol (control-plane side — the label/annotation transport mirrors how
the reference carries all its state on node objects):

1. After a slice's CC transition verifies locally, its node agent publishes
   the quote *digest* and mode as node annotations (``publish_quote``) —
   digests, not quotes: annotations are world-readable, and the digest is
   all a peer needs for the equality check.
2. Before a training job re-forms its DCN mesh, it (or the rolling
   orchestrator) calls ``verify_pool_attestation``: every slice in the pool
   must report (a) the expected mode, (b) a fresh-enough quote, and (c) the
   SAME runtime digest — heterogeneous digests mean some slice runs a
   different (possibly unmeasured) runtime and must not join the mesh.
3. The data-plane side then runs
   :func:`tpu_cc_manager.parallel.distributed.verify_dcn_mesh` for the
   collective-path health check before the first real step.
"""

from __future__ import annotations

import logging
import time

from tpu_cc_manager.kubeclient.api import KubeApi, node_labels
from tpu_cc_manager.tpudev.attestation import quote_digest
from tpu_cc_manager.tpudev.contract import AttestationQuote

log = logging.getLogger(__name__)

from tpu_cc_manager.labels import SLICE_ID_LABEL  # noqa: E402 - shared constant

QUOTE_ANNOTATION = "cloud.google.com/tpu-cc.attestation"


class PoolAttestationError(Exception):
    """The pool's slices do not present coherent attestation evidence."""


def quote_label_patch(quote: AttestationQuote | None) -> dict:
    """Label entries advertising a quote — or None-clears when there is no
    quote (mode off), so pool verification can't read stale evidence.

    Returned as a plain dict so callers can fold it into a single node
    merge-patch together with other coordination labels."""
    if quote is None:
        return {
            f"{QUOTE_ANNOTATION}.digest": None,
            f"{QUOTE_ANNOTATION}.mode": None,
            f"{QUOTE_ANNOTATION}.ts": None,
        }
    # Label values are constrained (63 chars, alphanum/-/_/.); pack the
    # payload into multiple labels instead of one JSON blob.
    return {
        f"{QUOTE_ANNOTATION}.digest": quote_digest(quote),
        f"{QUOTE_ANNOTATION}.mode": quote.mode,
        f"{QUOTE_ANNOTATION}.ts": str(int(time.time())),
    }


def publish_quote(api: KubeApi, node_name: str, quote: AttestationQuote) -> dict:
    """Publish a quote's digest+mode on the node as an annotation payload.

    Node annotations travel in metadata like labels, so the same
    merge-patch endpoint carries them (the in-tree kubeclient patches
    metadata.labels; annotations piggyback on a dedicated label-safe
    JSON value here to keep the client surface minimal)."""
    patch = quote_label_patch(quote)
    api.patch_node_labels(node_name, patch)
    payload = {
        "slice": quote.slice_id,
        "mode": quote.mode,
        "digest": patch[f"{QUOTE_ANNOTATION}.digest"],
        "ts": int(patch[f"{QUOTE_ANNOTATION}.ts"]),
    }
    log.info("published attestation for %s: %s", node_name, payload)
    return payload


def collect_pool_quotes(api: KubeApi, selector: str) -> dict[str, dict]:
    """slice_id -> {digest, mode, ts, nodes, missing} across matching nodes.

    Every host of a slice must attest, so hosts carrying the slice label but
    no quote are recorded in ``missing`` (not silently skipped), modes must
    agree across hosts (else ``mode`` becomes "MIXED"), and ``ts`` is the
    OLDEST host's timestamp so staleness checks see the worst host."""
    slices: dict[str, dict] = {}
    for node in api.list_nodes(selector):
        labels = node_labels(node)
        name = node["metadata"]["name"]
        digest = labels.get(f"{QUOTE_ANNOTATION}.digest")
        slice_id = labels.get(SLICE_ID_LABEL) or f"node/{name}"
        entry = slices.setdefault(
            slice_id,
            {"digest": None, "mode": None, "ts": None, "nodes": [], "missing": []},
        )
        if digest is None:
            entry["missing"].append(name)
            continue
        mode = labels.get(f"{QUOTE_ANNOTATION}.mode", "")
        ts = int(labels.get(f"{QUOTE_ANNOTATION}.ts", "0") or 0)
        entry["nodes"].append(name)
        entry["digest"] = digest if entry["digest"] in (None, digest) else "MIXED"
        entry["mode"] = mode if entry["mode"] in (None, mode) else "MIXED"
        entry["ts"] = ts if entry["ts"] is None else min(entry["ts"], ts)
    # Slices where no host attested at all keep digest None.
    return slices


def verify_pool_attestation(
    api: KubeApi,
    selector: str,
    expected_mode: str,
    expected_slices: int | None = None,
    max_age_s: float | None = 3600.0,
) -> dict[str, dict]:
    """Check every slice attests the expected mode with one common digest.

    Returns the slice map on success; raises PoolAttestationError with the
    full discrepancy list otherwise."""
    slices = collect_pool_quotes(api, selector)
    problems: list[str] = []
    if not any(e["nodes"] for e in slices.values()):
        problems.append("no slice published any attestation")
    if expected_slices is not None and len(slices) != expected_slices:
        problems.append(f"expected {expected_slices} slices, found {len(slices)}")
    now = time.time()
    digests = set()
    for sid, entry in sorted(slices.items()):
        if entry["missing"]:
            problems.append(
                f"slice {sid}: host(s) without attestation: "
                f"{sorted(entry['missing'])}"
            )
        if entry["digest"] is None:
            continue  # covered by the missing-hosts problem above
        if entry["digest"] == "MIXED":
            problems.append(f"slice {sid}: hosts disagree on runtime digest")
        else:
            digests.add(entry["digest"])
        if entry["mode"] == "MIXED":
            problems.append(f"slice {sid}: hosts disagree on attested mode")
        elif entry["mode"] != expected_mode:
            problems.append(
                f"slice {sid}: mode {entry['mode']!r} != expected {expected_mode!r}"
            )
        if max_age_s is not None and now - entry["ts"] > max_age_s:
            problems.append(f"slice {sid}: quote is stale ({int(now - entry['ts'])}s)")
    if len(digests) > 1:
        problems.append(
            f"slices report {len(digests)} distinct runtime digests: "
            f"{sorted(digests)}"
        )
    if problems:
        raise PoolAttestationError("; ".join(problems))
    log.info(
        "pool attestation verified: %d slice(s), digest=%s, mode=%s",
        len(slices), next(iter(digests)), expected_mode,
    )
    return slices


def pool_report(api: KubeApi, selector: str) -> str:
    """Human-readable attestation table (CLI helper)."""
    slices = collect_pool_quotes(api, selector)
    lines = [f"{'SLICE':<28} {'MODE':<10} {'DIGEST':<18} {'ATTESTED':<9} MISSING"]
    for sid, e in sorted(slices.items()):
        lines.append(
            f"{sid:<28} {str(e['mode'] or '-'):<10} "
            f"{str(e['digest'] or '-'):<18} {len(e['nodes']):<9} "
            f"{len(e['missing'])}"
        )
    return "\n".join(lines)
