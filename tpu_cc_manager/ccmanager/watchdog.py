"""Runtime-health watchdog: the monitor between reconciles.

The reconcile loop only probes runtime health during ``wait_ready`` — i.e.
while a mode change is in flight. A runtime that wedges BETWEEN reconciles
(crashed tpu-runtime unit, vanished device nodes, dead health port) kept
its last reported ``cc.ready.state`` indefinitely, and the probe layer
silently degraded to the weakest signal available (bare device-node
existence — VERDICT r5 weak #6) with nothing exporting which tier was
actually in use.

This watchdog closes both gaps:

- every ``interval_s`` (while no reconcile is in flight) it runs the
  backend's tiered probe (:meth:`TpuCcBackend.probe_runtime_health`) and
  exports the ACTIVE TIER and verdict as metrics
  (``tpu_cc_health_probe_tier{tier}``, ``tpu_cc_runtime_healthy``) — a
  fleet running on device-node-existence probes is now a dashboard fact;
- ``demote_after`` consecutive unhealthy probes flip
  ``cloud.google.com/tpu-cc.ready.state`` to ``"false"`` (the mode.state
  label is untouched — the mode is still committed; the node is just not
  currently serving it) with a ``CCRuntimeUnhealthy`` node event;
- ``restore_after`` consecutive healthy probes restore the ready value
  derived from the CURRENT mode.state label with a ``CCRuntimeRecovered``
  event — recovery is automatic, no label edit needed.

Hysteresis on both edges keeps a flapping probe from thrashing the label.
All clocks/sleeps are injectable; :meth:`tick` is the unit tests' and the
chaos soak's entry point, :meth:`run` the CLI's.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable

from tpu_cc_manager.kubeclient.api import (
    KubeApi,
    KubeApiError,
    caller_retry_attempts,
    classify_kube_error,
    node_labels,
)
from tpu_cc_manager.labels import (
    CC_MODE_STATE_LABEL,
    CC_READY_STATE_LABEL,
    ready_state_for,
)
from tpu_cc_manager.tpudev.contract import HealthProbe, TpuCcBackend, TpuError
from tpu_cc_manager.utils import metrics as metrics_mod
from tpu_cc_manager.utils import retry as retry_mod

log = logging.getLogger(__name__)

DEFAULT_INTERVAL_S = 30.0
DEFAULT_DEMOTE_AFTER = 3
DEFAULT_RESTORE_AFTER = 2


class RuntimeHealthWatchdog:
    def __init__(
        self,
        api: KubeApi,
        backend: TpuCcBackend,
        node_name: str,
        interval_s: float = DEFAULT_INTERVAL_S,
        demote_after: int = DEFAULT_DEMOTE_AFTER,
        restore_after: int = DEFAULT_RESTORE_AFTER,
        is_busy: Callable[[], bool] | None = None,
        emit_event: Callable[[str, str, str], None] | None = None,
        metrics: metrics_mod.MetricsRegistry | None = None,
        on_probe: Callable[[bool], None] | None = None,
        on_condemn: Callable[[], None] | None = None,
        defer_patch: Callable[[dict, BaseException], bool] | None = None,
        note_patched: Callable[[dict], None] | None = None,
    ) -> None:
        self.api = api
        self.backend = backend
        self.node_name = node_name
        self.interval_s = interval_s
        self.demote_after = max(1, demote_after)
        self.restore_after = max(1, restore_after)
        # "Busy" = a reconcile is in flight: the reconcile owns the ready
        # label then (wait_ready/verify run their own probes), so the
        # watchdog stands down instead of racing it.
        self.is_busy = is_busy or (lambda: False)
        self.emit_event = emit_event or (lambda *_: None)
        # Failure-containment hooks (ccmanager/remediation.py): every probe
        # verdict feeds the quarantine probation window, and the demote
        # edge condemns the host — aborting any in-flight slice barrier
        # with a fencing generation so ICI peers fail fast instead of
        # waiting out the barrier deadline on a host that just went
        # unhealthy.
        self.on_probe = on_probe or (lambda healthy: None)
        self.on_condemn = on_condemn or (lambda: None)
        # Disconnected-mode hook (manager.defer_patch_if_offline): a ready-
        # state write refused by a TOTAL apiserver outage is journaled as a
        # pending patch instead of silently dropped — a condemn that
        # happens while offline still reaches the labels, in journal
        # order, when connectivity returns.
        self.defer_patch = defer_patch
        # Superseding hook (manager.note_direct_patch): a ready-state
        # write that LANDS while stale deferred patches are still queued
        # must outrank them in journal order, or the eventual flush would
        # clobber it back.
        self.note_patched = note_patched
        self.metrics = metrics if metrics is not None else metrics_mod.REGISTRY
        self.degraded = False
        self._consecutive_unhealthy = 0
        self._consecutive_healthy = 0
        self._warned_weak_tier = False
        # Label writes ride the shared policy; one attempt when the client
        # retries internally (RestKube) so exactly one ladder runs per
        # logical call — fakes and chaos wrappers get the caller-side
        # ladder instead.
        self.retry_policy = retry_mod.RetryPolicy(
            max_attempts=caller_retry_attempts(api),
            base_delay_s=0.5,
            max_delay_s=5.0,
        )

    # ------------------------------------------------------------------

    def tick(self) -> HealthProbe | None:
        """One probe cycle; returns the probe (None when skipped busy)."""
        if self.is_busy():
            return None
        try:
            probe = self.backend.probe_runtime_health()
        except TpuError as e:
            # A probe that cannot even run is an unhealthy verdict from no
            # tier at all — the weakest possible state.
            probe = HealthProbe("none", False, f"probe raised: {e}")
        self.metrics.set_health_tier(probe.tier, probe.strength, probe.healthy)
        try:
            self.on_probe(probe.healthy)
        except Exception as e:  # noqa: BLE001 - probation must not stop probing
            log.warning("watchdog on_probe hook failed: %s", e)
        if probe.tier == "device-node" and not self._warned_weak_tier:
            # The silent-weakest-probe fallback, made loud exactly once.
            log.warning(
                "runtime health is probed by device-node existence only — "
                "the weakest tier (nodes persist across a wedged runtime); "
                "configure CC_RUNTIME_HEALTH_PORT or a probe command"
            )
            self._warned_weak_tier = True
        if probe.healthy:
            self._consecutive_unhealthy = 0
            self._consecutive_healthy += 1
            if self.degraded and self._consecutive_healthy >= self.restore_after:
                self._restore(probe)
        else:
            self._consecutive_healthy = 0
            self._consecutive_unhealthy += 1
            log.warning(
                "runtime health probe unhealthy (%d/%d, tier=%s): %s",
                self._consecutive_unhealthy, self.demote_after,
                probe.tier, probe.detail,
            )
            if self._consecutive_unhealthy >= self.demote_after:
                # Runs on EVERY sustained-unhealthy tick, not only the
                # closed->degraded transition: a reconcile may have
                # rewritten ready=true while the runtime is still wedged,
                # and an in-memory latch must not stop the re-demote. The
                # patch is idempotent; the event/metric fire only on the
                # transition.
                self._demote(probe, first=not self.degraded)
        return probe

    def _patch_ready(self, value: str) -> None:
        try:
            self.retry_policy.call(
                lambda: self.api.patch_node_labels(
                    self.node_name, {CC_READY_STATE_LABEL: value}
                ),
                op="watchdog.patch_ready",
                classify=classify_kube_error,
            )
            if self.note_patched is not None:
                self.note_patched({CC_READY_STATE_LABEL: value})
        except KubeApiError as e:
            patch = {CC_READY_STATE_LABEL: value}
            if self.defer_patch is not None and self.defer_patch(patch, e):
                log.warning(
                    "watchdog: apiserver offline; %s=%s deferred to the "
                    "intent journal", CC_READY_STATE_LABEL, value,
                )
                return
            raise

    def _demote(self, probe: HealthProbe, first: bool = True) -> None:
        if self.is_busy():
            # A reconcile started while this tick's (slow) probe ran; it
            # owns the ready label now and may just have restored the
            # runtime — a demote computed from pre-reconcile probes must
            # not overwrite it. The next tick re-evaluates fresh.
            log.info("watchdog: reconcile started mid-probe; demote skipped")
            return
        try:
            self._patch_ready("false")
        except KubeApiError as e:
            log.error("watchdog could not demote ready state: %s", e)
            return  # stay un-degraded; next tick retries the whole demote
        self.degraded = True
        if not first:
            log.debug("watchdog: not-ready state re-asserted")
            return
        try:
            # Condemn on the demote EDGE only: peers mid-barrier stop
            # waiting on this host now, not once per re-asserting tick.
            self.on_condemn()
        except Exception as e:  # noqa: BLE001 - fencing peers is best-effort
            log.warning("watchdog on_condemn hook failed: %s", e)
        self.metrics.record_failure("runtime-unhealthy")
        log.error(
            "sustained runtime degradation (%d consecutive unhealthy "
            "probes, tier=%s): %s — %s flipped to 'false'",
            self._consecutive_unhealthy, probe.tier, probe.detail,
            CC_READY_STATE_LABEL,
        )
        self.emit_event(
            "Warning", "CCRuntimeUnhealthy",
            f"TPU runtime unhealthy for {self._consecutive_unhealthy} "
            f"consecutive probes (tier={probe.tier}): {probe.detail}",
        )

    def _restore(self, probe: HealthProbe) -> None:
        if self.is_busy():  # same mid-probe race as _demote
            log.info("watchdog: reconcile started mid-probe; restore deferred")
            return
        try:
            state = node_labels(
                self.retry_policy.call(
                    lambda: self.api.get_node(self.node_name),
                    op="watchdog.get_node",
                    classify=classify_kube_error,
                )
            ).get(CC_MODE_STATE_LABEL, "")
            self._patch_ready(ready_state_for(state))
        except KubeApiError as e:
            log.error("watchdog could not restore ready state: %s", e)
            return  # still degraded; next healthy tick retries
        self.degraded = False
        log.info(
            "runtime recovered (%d consecutive healthy probes, tier=%s); "
            "%s restored for state=%s",
            self._consecutive_healthy, probe.tier,
            CC_READY_STATE_LABEL, state or "<unset>",
        )
        self.emit_event(
            "Normal", "CCRuntimeRecovered",
            f"TPU runtime healthy again (tier={probe.tier}); "
            "ready state restored",
        )

    # ------------------------------------------------------------------

    def run(self, stop: threading.Event) -> None:
        """Probe every ``interval_s`` until ``stop`` is set."""
        log.info(
            "runtime-health watchdog started (interval=%.0fs, demote_after=%d, "
            "restore_after=%d)",
            self.interval_s, self.demote_after, self.restore_after,
        )
        while not stop.is_set():
            try:
                self.tick()
            except Exception as e:  # noqa: BLE001 - the watchdog must survive
                # anything (it is the component that reports wedges, so it
                # must not wedge): log and keep ticking.
                log.error("watchdog tick failed: %s", e, exc_info=True)
            stop.wait(self.interval_s)

    def start(self, stop: threading.Event) -> threading.Thread:
        t = threading.Thread(
            target=self.run, args=(stop,), name="runtime-health-watchdog",
            daemon=True,
        )
        t.start()
        return t


def start_from_env(
    api: KubeApi,
    backend: TpuCcBackend,
    node_name: str,
    stop: threading.Event,
    is_busy: Callable[[], bool] | None = None,
    emit_event: Callable[[str, str, str], None] | None = None,
    metrics: metrics_mod.MetricsRegistry | None = None,
    on_probe: Callable[[bool], None] | None = None,
    on_condemn: Callable[[], None] | None = None,
    defer_patch: Callable[[dict, BaseException], bool] | None = None,
    note_patched: Callable[[dict], None] | None = None,
) -> RuntimeHealthWatchdog | None:
    """CLI wiring: CC_WATCHDOG_INTERVAL_S (0 disables),
    CC_WATCHDOG_DEMOTE_AFTER, CC_WATCHDOG_RESTORE_AFTER."""
    import os

    interval = float(
        os.environ.get("CC_WATCHDOG_INTERVAL_S", str(DEFAULT_INTERVAL_S))
    )
    if interval <= 0:
        log.info("runtime-health watchdog disabled (CC_WATCHDOG_INTERVAL_S<=0)")
        return None
    watchdog = RuntimeHealthWatchdog(
        api,
        backend,
        node_name,
        interval_s=interval,
        demote_after=int(
            os.environ.get("CC_WATCHDOG_DEMOTE_AFTER", str(DEFAULT_DEMOTE_AFTER))
        ),
        restore_after=int(
            os.environ.get(
                "CC_WATCHDOG_RESTORE_AFTER", str(DEFAULT_RESTORE_AFTER)
            )
        ),
        is_busy=is_busy,
        emit_event=emit_event,
        metrics=metrics,
        on_probe=on_probe,
        on_condemn=on_condemn,
        defer_patch=defer_patch,
        note_patched=note_patched,
    )
    watchdog.start(stop)
    return watchdog
