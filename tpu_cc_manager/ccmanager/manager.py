"""CCManager: the per-node reconciler.

Reference analogue: the CCManager class (main.py:105-695; call stacks in
SURVEY.md §3). The protocol is preserved — desired mode read from a node
label, idempotency check, drain-before-reconfigure, phased
stage/reset/verify, crash-as-retry on unrecoverable misconfiguration,
``failed`` state label on errors, watch with resourceVersion tracking /
410 resync / consecutive-error cap — with the TPU-structural changes:

- the device unit is the ICI slice, so stage/reset/wait act on the whole
  chip set (tpudev/contract.py);
- verification is upgraded from "query equals desired" to query + slice
  attestation + an optional end-to-end JAX smoke workload (SURVEY.md §3.4);
- every phase is timed (utils/metrics.py) because the north-star metric is
  the drain→CC-on→ready latency (BASELINE.md).

Reference bugs deliberately fixed (SURVEY.md §8): ``time`` is imported (§8.1),
there is no dead ``last_label`` state (§8.2), label writes are merge-patches
(§8.3).
"""

from __future__ import annotations

import json
import logging
import os
import sys
import threading
import time
from typing import Callable

from tpu_cc_manager.ccmanager import intent_journal as intent_mod
from tpu_cc_manager.ccmanager import slicecoord
from tpu_cc_manager.drain import evict, state
from tpu_cc_manager.kubeclient.api import (
    KubeApi,
    KubeApiError,
    node_labels,
    resource_version,
)
from tpu_cc_manager import labels as labels_mod
from tpu_cc_manager.labels import (
    CC_MODE_LABEL,
    MODE_DEVTOOLS,
    MODE_OFF,
    MODE_SLICE,
    STATE_FAILED,
    VALID_MODES,
    canonical_mode,
    label_safe,
)
from tpu_cc_manager.obs import journal as journal_mod
from tpu_cc_manager.obs import trace as trace_mod
from tpu_cc_manager.tpudev import attestation
from tpu_cc_manager.tpudev.contract import SliceTopology, TpuCcBackend, TpuChip, TpuError
from tpu_cc_manager.utils import locks as locks_mod
from tpu_cc_manager.utils import metrics as metrics_mod
from tpu_cc_manager.utils import retry as retry_mod

log = logging.getLogger(__name__)


class ModeUnsupported(TpuError):
    """The requested mode cannot run on this node's hardware — a stable
    misconfiguration that fails soft (failed label + reason), unlike mixed
    capability which keeps the reference's crash-as-retry."""

    def __init__(self, message: str, reason: str):
        super().__init__(message)
        self.reason = reason


DEFAULT_READINESS_FILE = "/run/tpu/validations/.tpu-cc-manager-ctr-ready"
# Reference operational constants (SURVEY.md §6).
WATCH_TIMEOUT_S = 300
WATCH_RECONNECT_DELAY_S = 5.0
MAX_CONSECUTIVE_WATCH_ERRORS = 10
DEFAULT_READY_TIMEOUT_S = 300.0

# Preemption fast-drain (spot/preemptible nodes): the hard termination
# deadline the platform gives between notice and kill, and how often the
# monitor polls the backend's notice source (GCE: metadata-server
# ``instance/preempted``). CC_PREEMPTION_DEADLINE_S=0 disables the
# monitor entirely.
DEFAULT_PREEMPTION_DEADLINE_S = 30.0
DEFAULT_PREEMPTION_POLL_S = 5.0

#: Node annotation carrying the handoff record of a transition a
#: preemption notice interrupted: {mode, phase, chips, slice_id, from,
#: ts} as JSON. Published by the departing agent BEFORE the kill and
#: consumed by the replacement node's agent at startup — the preempted
#: VM's disk (and with it the intent journal) dies in the reclaim, so
#: the apiserver copy is the only record that reaches the successor.
HANDOFF_ANNOTATION = labels_mod.HANDOFF_ANNOTATION

#: Spare pre-staging (zero-bounce flips): the request annotation names
#: the mode to pre-stage; the status annotation carries the agent's JSON
#: record {"mode", "prior", "seconds", "ts"} once the pre-staged flip
#: completed. See labels.py for the full protocol.
PRESTAGE_ANNOTATION = labels_mod.PRESTAGE_ANNOTATION
PRESTAGED_ANNOTATION = labels_mod.PRESTAGED_ANNOTATION


class _PipelineTask:
    """One overlapped pipeline step on a worker thread, with the caller's
    trace context propagated so its phase spans nest under the reconcile
    root. ``join()`` re-raises whatever escaped the step — BaseException
    included, so a modeled SIGKILL inside an overlapped step unwinds the
    main pipeline exactly like one on the serial path (intent left open,
    no except-Exception cleanup)."""

    def __init__(self, name: str, fn: Callable[[], None]) -> None:
        self._error: BaseException | None = None

        def run() -> None:
            try:
                fn()
            except BaseException as e:  # noqa: BLE001  # cclint: crash-ok(worker trampoline - join re-raises, SIGKILL unwinds the owning pipeline)
                self._error = e

        self._thread = threading.Thread(
            target=trace_mod.in_current_context(run),
            name=f"cc-pipeline-{name}", daemon=True,
        )
        self._thread.start()

    def join(self) -> None:
        self._thread.join()
        if self._error is not None:
            raise self._error

    def join_quiet(self) -> BaseException | None:
        """Join without raising; returns the captured error (the caller
        is already on a failure path and must not mask its own cause)."""
        self._thread.join()
        return self._error


class _ReadmitOnce:
    """Runs the readmit bracket exactly once — either early, overlapped
    with the smoke workload (``start_async``), or synchronously from the
    owner's finally (``finish``). ``finish`` always represents the
    bracket's true outcome: it joins an early run and re-raises its
    failure, so the caller's drain-intent close still only happens after
    a readmit that actually succeeded."""

    _SYNC = object()  # claimed by finish(); any later start_async no-ops

    def __init__(
        self, fn: Callable[[], None],
        on_start: Callable[[], None] | None = None,
    ) -> None:
        self._fn = fn
        self._on_start = on_start
        self._task: object | None = None
        self._lock = locks_mod.make_lock("manager.readmit-once")

    def start_async(self) -> None:
        with self._lock:
            if self._task is not None:
                return
            if self._on_start is not None:
                self._on_start()
            self._task = _PipelineTask("readmit", self._fn)

    def finish(self) -> None:
        with self._lock:
            task = self._task
            if task is None:
                self._task = self._SYNC
        if isinstance(task, _PipelineTask):
            task.join()
        elif task is None:
            self._fn()


class CCManager:
    def __init__(
        self,
        api: KubeApi,
        backend: TpuCcBackend,
        node_name: str,
        default_mode: str = "on",
        host_cc_capable: bool = True,
        operator_namespace: str | None = None,
        evict_components: bool | None = None,
        smoke_workload: str | None = None,
        smoke_runner: Callable[[str], dict] | None = None,
        eviction_timeout_s: float | None = None,
        eviction_poll_interval_s: float = evict.DEFAULT_POLL_INTERVAL_S,
        strict_eviction: bool | None = None,
        drain_ack_timeout_s: float | None = None,
        ready_timeout_s: float = DEFAULT_READY_TIMEOUT_S,
        slice_barrier_timeout_s: float | None = None,
        slice_barrier_poll_interval_s: float = 1.0,
        allow_fake_quotes: bool | None = None,
        readiness_file: str | None = None,
        watch_timeout_s: int = WATCH_TIMEOUT_S,
        reconnect_delay_s: float = WATCH_RECONNECT_DELAY_S,
        max_watch_errors: int = MAX_CONSECUTIVE_WATCH_ERRORS,
        retry_backoff_s: float | None = None,
        retry_backoff_max_s: float | None = None,
        metrics: metrics_mod.MetricsRegistry | None = None,
        journal: journal_mod.Journal | None = None,
        remediation=None,
        intent_journal: intent_mod.IntentJournal | None = None,
        offline_grace_s: float | None = None,
        use_slice_informer: bool | None = None,
        preemption_deadline_s: float | None = None,
        preemption_poll_s: float | None = None,
        pipeline_transitions: bool | None = None,
        smoke_digest_fastpath: bool | None = None,
        smoke_warmup: bool | None = None,
        smoke_warmup_factory: Callable[[str], object] | None = None,
        state_dir: str | None = None,
        prestage: bool | None = None,
    ) -> None:
        self.api = api
        self.backend = backend
        self.node_name = node_name
        self.default_mode = canonical_mode(default_mode)
        self.host_cc_capable = host_cc_capable
        # Env-var configuration, same names modulo prefix as the reference
        # (main.py:116-119: OPERATOR_NAMESPACE, EVICT_OPERATOR_COMPONENTS).
        self.operator_namespace = operator_namespace or os.environ.get(
            "OPERATOR_NAMESPACE", "tpu-operator"
        )
        if evict_components is None:
            evict_components = os.environ.get(
                "EVICT_OPERATOR_COMPONENTS", "true"
            ).lower() in ("true", "1", "yes")
        self.evict_components = evict_components
        self.smoke_workload = (
            smoke_workload
            if smoke_workload is not None
            else os.environ.get("CC_SMOKE_WORKLOAD", "none")
        )
        self.smoke_runner = smoke_runner
        if eviction_timeout_s is None:
            eviction_timeout_s = float(
                os.environ.get(
                    "CC_EVICTION_TIMEOUT_S", evict.DEFAULT_EVICTION_TIMEOUT_S
                )
            )
        self.eviction_timeout_s = eviction_timeout_s
        self.eviction_poll_interval_s = eviction_poll_interval_s
        # Workload drain handshake (drain/handshake.py): how long registered
        # training jobs get to checkpoint+ack before components are paused.
        # 0 disables (the reference has no workload protocol at all).
        if drain_ack_timeout_s is None:
            drain_ack_timeout_s = float(
                os.environ.get("CC_DRAIN_ACK_TIMEOUT_S", "0")
            )
        self.drain_ack_timeout_s = drain_ack_timeout_s
        # The reference proceeds to the hardware phase on a drain timeout
        # (gpu_operator_eviction.py:205-207) — risky but deliberate; strict
        # mode (CC_STRICT_EVICTION=1) fails the reconcile instead
        # (SURVEY.md §8.5: "preserve behavior behind a flag").
        if strict_eviction is None:
            strict_eviction = os.environ.get(
                "CC_STRICT_EVICTION", ""
            ).lower() in ("true", "1", "yes")
        self.strict_eviction = strict_eviction
        self.ready_timeout_s = ready_timeout_s
        if slice_barrier_timeout_s is None:
            slice_barrier_timeout_s = float(
                os.environ.get(
                    "CC_SLICE_BARRIER_TIMEOUT_S",
                    slicecoord.DEFAULT_BARRIER_TIMEOUT_S,
                )
            )
        self.slice_barrier_timeout_s = slice_barrier_timeout_s
        self.slice_barrier_poll_interval_s = slice_barrier_poll_interval_s
        # Slice-peer informer (ccmanager/informer.py, CC_SLICE_INFORMER):
        # one watch over this node's slice membership label replaces the
        # barrier's 1/s peer listings — N hosts × barrier-deadline seconds
        # of O(slice) listings collapse to O(changes) watch events. Opt-in
        # via env (the DaemonSet sets it); without it the barrier polls
        # listings exactly as before.
        if use_slice_informer is None:
            use_slice_informer = os.environ.get(
                "CC_SLICE_INFORMER", ""
            ).lower() in ("true", "1", "yes")
        self.use_slice_informer = use_slice_informer
        self._peer_informer = None
        if allow_fake_quotes is None:
            env = os.environ.get("CC_ALLOW_FAKE_QUOTES")
            if env is not None:
                allow_fake_quotes = env.lower() in ("true", "1", "yes")
            else:
                # Fake-platform quotes are trustworthy exactly when the
                # operator explicitly chose the fake device layer; a
                # production (tpuvm) verifier must reject them
                # (tpudev/attestation.py).
                from tpu_cc_manager.tpudev.fake import FakeTpuBackend

                allow_fake_quotes = isinstance(backend, FakeTpuBackend)
        self.allow_fake_quotes = allow_fake_quotes
        self.readiness_file = readiness_file or os.environ.get(
            "CC_READINESS_FILE", DEFAULT_READINESS_FILE
        )
        self.watch_timeout_s = watch_timeout_s
        self.reconnect_delay_s = reconnect_delay_s
        self.max_watch_errors = max_watch_errors
        # Watch-reconnect backoff through the shared policy: full jitter
        # under an exponential cap keyed on the consecutive-error count, so
        # a pool of agents doesn't reconnect to a flapping apiserver in
        # lockstep every reconnect_delay_s (the reference's fixed 5 s).
        self._reconnect_policy = retry_mod.RetryPolicy(
            base_delay_s=max(0.001, reconnect_delay_s),
            max_delay_s=max(reconnect_delay_s, 60.0),
        )
        # Failed-reconcile retry with exponential backoff: the reference
        # leaves a transiently-failed node 'failed' until the label is
        # touched again (main.py only re-applies on label *change*); a
        # periodic re-apply is cheap and converges. <=0 disables.
        if retry_backoff_s is None:
            retry_backoff_s = float(os.environ.get("CC_RETRY_BACKOFF_S", "5"))
        self.retry_backoff_s = retry_backoff_s
        if retry_backoff_max_s is None:
            retry_backoff_max_s = float(
                os.environ.get("CC_RETRY_BACKOFF_MAX_S", "300")
            )
        self.retry_backoff_max_s = retry_backoff_max_s
        self.metrics = metrics if metrics is not None else metrics_mod.REGISTRY
        # Span journal for the reconcile trace (obs/): every phase, drain
        # step, barrier wait, attestation and smoke run of one reconcile
        # shares one trace_id, served at /tracez and (optionally,
        # CC_TRACE_FILE) written as JSONL.
        self.journal = journal if journal is not None else journal_mod.JOURNAL
        # True while a reconcile (set_cc_mode) is in flight; the CLI's
        # shutdown path consults it so a hard exit never interrupts a
        # half-applied hardware transition when grace time remains.
        self.reconciling = False
        # Whether the most recent failure could plausibly clear on a fast
        # retry. Stable misconfigurations (ModeUnsupported, invalid mode)
        # set this False: they are retried only at the slow
        # retry_backoff_max_s cadence — enough that a later hardware/pool
        # fix still converges without a label edit, without re-failing an
        # identical reconcile every few seconds.
        self.retryable_failure = True
        # Machine-readable reason of the most recent failure (what the
        # failed.reason label carries); feeds the remediation ladder.
        self.last_failure_reason: str | None = None
        # Escalating remediation ladder (ccmanager/remediation.py): fed a
        # note per reconcile outcome from the watch loop; while it holds
        # the node quarantined, reconciles are deferred (slow re-check
        # cadence) instead of hammering known-bad hardware. None disables.
        self.remediation = remediation
        # Node-local write-ahead intent log (ccmanager/intent_journal.py):
        # every hardware-effecting transition and drain bracket is
        # journaled intent→(committed|aborted), so a crash-restart replays
        # the journal BEFORE touching the apiserver and completes or rolls
        # back the in-flight transition from local truth alone. None
        # disables (behavior reverts to apiserver-only state).
        self.intents = intent_journal
        # Disconnected-mode ladder: after CC_OFFLINE_GRACE_S of total
        # apiserver outage the agent keeps serving its last-known desired
        # mode and defers label writes into the journal as pending
        # patches, flushed idempotently (RMW) on reconnect.
        self.offline = intent_mod.OfflineTracker(offline_grace_s)
        self._flushing_patches = False
        # Preemption fast-drain (spot/preemptible nodes): the hard
        # termination deadline the platform's notice leaves us, and how
        # often to poll the backend's notice source. deadline<=0 or
        # poll<=0 disables the monitor.
        if preemption_deadline_s is None:
            preemption_deadline_s = float(
                os.environ.get(
                    "CC_PREEMPTION_DEADLINE_S",
                    str(DEFAULT_PREEMPTION_DEADLINE_S),
                )
            )
        self.preemption_deadline_s = preemption_deadline_s
        if preemption_poll_s is None:
            preemption_poll_s = float(
                os.environ.get(
                    "CC_PREEMPTION_POLL_S", str(DEFAULT_PREEMPTION_POLL_S)
                )
            )
        self.preemption_poll_s = preemption_poll_s
        # Pipelined transitions (default on; CC_PIPELINE_TRANSITIONS=0
        # restores the fully serial reference ordering): stage (and the
        # slice barrier's staged publication) overlaps the pod-drain
        # bracket, attestation prep overlaps wait_ready, and re-admission
        # overlaps the smoke workload. The hard orderings are untouched —
        # this host never resets before its own drain completed, and the
        # drain intent closes only after readmit actually succeeded.
        if pipeline_transitions is None:
            pipeline_transitions = os.environ.get(
                "CC_PIPELINE_TRANSITIONS", "1"
            ).lower() not in ("0", "false", "no")
        self.pipeline_transitions = pipeline_transitions
        # Attestation-digest smoke fast path (CC_SMOKE_DIGEST_FAST_PATH,
        # default off): when a flip lands on a runtime whose measured
        # digest equals the last digest a FULL smoke verified, the smoke
        # is skipped in favor of the attest-only verify. A changed digest
        # always falls through to the full smoke.
        if smoke_digest_fastpath is None:
            smoke_digest_fastpath = os.environ.get(
                "CC_SMOKE_DIGEST_FAST_PATH", ""
            ).lower() in ("true", "1", "yes")
        self.smoke_digest_fastpath = smoke_digest_fastpath
        # Boot-wait∥COMPILE smoke warmup (CC_SMOKE_WARMUP, default on;
        # effective only while pipeline_transitions is on): the smoke
        # subprocess is launched alongside wait_ready in a compile-only
        # warmup mode (smoke/runner.py dispatch gate) and its device
        # dispatch is released only after the runtime is ready AND
        # attestation passed — the ~20 s boot wait absorbs the smoke's
        # interpreter-start + jax-import + compile span. An injected
        # smoke_runner (tests, custom harnesses) disables it unless a
        # warmup factory is injected too.
        if smoke_warmup is None:
            smoke_warmup = os.environ.get(
                "CC_SMOKE_WARMUP", "1"
            ).lower() not in ("0", "false", "no")
        self.smoke_warmup = smoke_warmup
        self.smoke_warmup_factory = smoke_warmup_factory
        # Where the verified-digest record lives (the backend state dir,
        # like the intent journal); None disables persistence — the fast
        # path then never has a digest on record and every flip runs the
        # full smoke.
        if state_dir is None:
            state_dir = (
                os.environ.get("CC_STATE_DIR")
                or getattr(backend, "state_dir", None)
            )
        self._state_dir = state_dir
        self._preemption_stop: threading.Event | None = None
        self._preemption_thread: threading.Thread | None = None
        self._preemption_handled = False
        # The transition currently in flight (mode, chip indices, phase,
        # slice identity), maintained by _apply_direct so the preemption
        # handler — running on the monitor thread, concurrently with a
        # reconcile blocked in a barrier wait — knows exactly what to
        # hand off. None outside the hardware pipeline. Shared between
        # the reconcile thread (writes) and the preemption monitor
        # (reads), hence the dedicated leaf lock.
        self._transition_lock = locks_mod.make_lock("manager.transition")
        self._inflight_transition: dict | None = None  # cclint: guarded-by(_transition_lock)
        # A predecessor's handoff record consumed at startup; retired
        # (annotation cleared + outcome=resumed counted) after the first
        # successful reconcile completes the handed-off flip.
        self._handoff: dict | None = None
        # Event dedup state (see _emit_node_event).
        self._last_event_key: tuple[str, str, str] | None = None
        # Cross-process trace stitching (labels.ROLLOUT_TRACE_LABEL):
        # the orchestrator span identity stamped into the most recent
        # desired-mode patch, adopted as the reconcile root span's
        # remote parent so /tracez renders one causal tree from `ctl
        # rollout` down through this node's drain/reset/smoke. Written
        # and read on the watch-loop thread only (the reconcile runs
        # inline in it); a stale value is truthful — it names the
        # rollout that most recently set the desired mode, which IS the
        # causal parent of every reconcile converging toward it,
        # retries included.
        self._rollout_trace_parent: tuple[str, str] | None = None
        # Verifier-challenge re-attestation (multislice.py): the last
        # challenge nonce this agent answered, so the MODIFIED event our
        # own answer generates doesn't loop into another answer.
        self._answered_challenge_nonce: str | None = None
        # Spare pre-staging (zero-bounce flips, CC_PRESTAGE, default on):
        # a PRESTAGE annotation asks this agent to run the full journaled
        # transition + warmup to a mode AHEAD of the rollout wave that
        # will request it, publish the truthful state label, and HOLD
        # there until the desired label catches up (or the annotation is
        # deleted — the abort path). Caches are written on the watch-loop
        # thread only, like _rollout_trace_parent.
        if prestage is None:
            prestage = os.environ.get(
                "CC_PRESTAGE", "1"
            ).lower() not in ("0", "false", "no")
        self.prestage = prestage
        self._prestage_request: str | None = None
        self._prestaged: dict | None = None
        # In-process copy of the last COMPLETED prestage record: watch
        # events queued behind the (long) prestage pass carry stale node
        # snapshots from mid-transition, and trusting them alone would
        # re-run the pass once per queued event and let a stale view
        # drop the hold. This copy is authoritative until consumed,
        # aborted, or superseded by a different-mode reconcile.
        self._prestage_done: dict | None = None
        self._in_prestage = False
        # True when the most recent reconcile resolved as a prestage
        # HOLD (no hardware touched, desired deliberately not applied):
        # the success-path housekeeping must not treat it as a completed
        # desired-mode flip — consuming the prestage record there would
        # clear the very annotations the hold runs on.
        self._prestage_held = False

    # ------------------------------------------------------------------
    # Label plumbing
    # ------------------------------------------------------------------

    def _emit_node_event(self, type_: str, reason: str, message: str) -> None:
        """Best-effort core/v1 Event on this node (`kubectl describe node`
        visibility — the reference's only outward signals are labels and a
        file; SURVEY.md §5). Deduplicated on (type, reason, message) so
        idempotent re-applies and retry loops don't spam the event stream;
        never fails a reconcile. Not all clients support events — the
        KubeApi default raises — hence the broad non-fatal handling."""
        key = (type_, reason, message)
        if key == self._last_event_key:
            return
        try:
            now = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
            # Events for cluster-scoped objects (Node) must live in the
            # "default" namespace — apiserver validation rejects any other
            # when involvedObject.namespace is empty.
            metadata: dict = {"generateName": "tpu-cc-manager."}
            trace_id = trace_mod.current_trace_id()
            if trace_id is not None:
                # kubectl-describe readers can jump from the event to the
                # reconcile's span tree (/tracez?trace_id=...).
                metadata["annotations"] = {
                    labels_mod.TRACE_ID_ANNOTATION: trace_id
                }
            self.api.create_event("default", {
                "metadata": metadata,
                "involvedObject": {
                    "kind": "Node", "name": self.node_name, "apiVersion": "v1",
                },
                "reason": reason,
                "message": message[:1024],
                "type": type_,
                "source": {"component": "tpu-cc-manager", "host": self.node_name},
                "firstTimestamp": now,
                "lastTimestamp": now,
                "count": 1,
            })
            self._last_event_key = key
        except Exception as e:  # noqa: BLE001 - "never fails a reconcile"
            # must hold for ANY failure shape (a malformed 201 body raises
            # JSONDecodeError, not KubeApiError) — a verified mode change
            # must not be re-reported failed over a convenience signal.
            log.debug("event emission failed (non-fatal): %s", e)

    def _record_failure(self, reason: str) -> None:
        """Count a failed reconcile and remember its reason for the
        remediation ladder."""
        self.last_failure_reason = reason
        self.metrics.record_failure(reason)

    def _publish_trace_annotation(self, trace_id: str) -> None:
        """Advertise the last reconcile's trace id on the node
        (labels.TRACE_ID_ANNOTATION) so operators can jump from `ctl
        status` to /tracez?trace_id=. Best-effort, like every other
        coordination metadata write: a minimal client without
        annotation patching (or an apiserver blip) must never fail a
        verified mode change."""
        try:
            self.api.patch_node_annotations(
                self.node_name,
                {labels_mod.TRACE_ID_ANNOTATION: trace_id},
            )
        except Exception as e:  # noqa: BLE001 - advisory metadata only
            log.debug(
                "could not publish trace-id annotation (non-fatal): %s", e
            )

    # ------------------------------------------------------------------
    # Apiserver connectivity + intent journal (disconnected mode)
    # ------------------------------------------------------------------

    def _note_api_ok(self) -> None:
        """An apiserver interaction succeeded: reset the outage clock and,
        if deferred label writes are queued in the intent journal, flush
        them — this is the reconnect edge of the disconnected-mode
        ladder."""
        self.offline.note_success()
        self.metrics.set_apiserver_connected(True)
        self.metrics.set_offline_seconds(0.0)
        if self.intents is not None and self.intents.has_pending_patches():
            self._flush_pending_patches()

    def _note_api_err(self, e: BaseException | None = None) -> None:
        """A transport-level apiserver failure: advance the outage clock
        (HTTP-status errors are a server that ANSWERED and never count)."""
        if e is not None and not intent_mod.is_outage_error(e):
            return
        self.offline.note_failure()
        self.metrics.set_apiserver_connected(False)
        self.metrics.set_offline_seconds(self.offline.offline_seconds)

    def _flush_pending_patches(self) -> None:
        """Flush label writes deferred while disconnected. Idempotent RMW,
        not blind replay: the merged pending state is compared against the
        node's CURRENT labels and only differing keys are patched — a
        value some other writer (or a fresher reconcile) already landed is
        neither duplicated nor clobbered back. A failed flush keeps the
        patches queued for the next successful interaction."""
        if self._flushing_patches or self.intents is None:
            return
        self._flushing_patches = True
        try:
            pending, upto = self.intents.pending_snapshot()
            if not pending:
                return
            node = self.api.get_node(self.node_name)
            labels = node_labels(node)
            patch: dict = {}
            for key, value in pending.items():
                if value is None:
                    if key in labels:
                        patch[key] = None
                elif labels.get(key) != value:
                    patch[key] = value
            if patch:
                self.api.patch_node_labels(self.node_name, patch)
            # Only the snapshot is flushed: a patch deferred concurrently
            # (seq > upto) stays queued for the next flush.
            self.intents.patches_flushed(upto)
            log.info(
                "flushed %d deferred label write(s) after reconnect "
                "(%d key(s) still differed and were patched)",
                len(pending), len(patch),
            )
        except KubeApiError as e:
            self._note_api_err(e)
            log.warning("deferred-patch flush failed; will retry: %s", e)
        except intent_mod.JournalError as e:
            log.warning("could not mark deferred patches flushed: %s", e)
        finally:
            self._flushing_patches = False

    def _defer_patch(self, patch) -> bool:
        """Queue a label write in the intent journal for the reconnect
        flush; False when there is no journal (or it cannot persist)."""
        if self.intents is None:
            return False
        try:
            self.intents.defer_patch(dict(patch))
        except intent_mod.JournalError as e:
            log.warning("could not defer label write to the journal: %s", e)
            return False
        self.metrics.record_deferred_patch()
        return True

    def note_direct_patch(self, patch) -> None:
        """A label write LANDED directly while deferred patches are still
        queued (an earlier flush failed or is racing): journal the fresh
        values as a superseding patch record, so the eventual flush's
        journal-order merge carries them and cannot clobber the labels
        back to the stale pre-outage values."""
        if self.intents is None or not self.intents.has_pending_patches():
            return
        try:
            self.intents.defer_patch(dict(patch))
        except intent_mod.JournalError as e:
            log.warning(
                "could not journal a superseding label write: %s", e
            )

    def defer_patch_if_offline(self, patch, error: BaseException) -> bool:
        """Hook for co-located writers (the runtime-health watchdog): when
        a label write failed on a transport-level error during an ENGAGED
        outage, journal it as a pending patch and report it handled. The
        watchdog's condemn-while-offline rides this: the demote patch is
        deferred and flushed, in journal order, on reconnect."""
        if not intent_mod.is_outage_error(error):
            return False
        self._note_api_err(error)
        if not self.offline.engaged:
            return False
        return self._defer_patch(patch)

    def _report_state(
        self, state_value: str, reason: str | None = None,
        force_defer: bool = False,
    ) -> None:
        """Report actual state like drain/state.py, but disconnected-
        aware: when the apiserver is in an engaged outage (or
        ``force_defer``, the journal-replay path while still dark), the
        patch is journaled as a pending write instead of failing the
        reconcile — the node's local truth keeps advancing and the labels
        catch up idempotently on reconnect."""
        patch = state.state_label_patch(state_value, reason)
        try:
            state.set_cc_state_label(
                self.api, self.node_name, state_value, reason=reason
            )
            # BEFORE the reconnect-edge flush: if stale pre-outage patches
            # are still queued (a flush failed earlier), this fresher
            # direct write supersedes them in journal order.
            self.note_direct_patch(patch)
            self._note_api_ok()
        except KubeApiError as e:
            self._note_api_err(e)
            if (
                intent_mod.is_outage_error(e)
                and (force_defer or self.offline.engaged)
                and self._defer_patch(patch)
            ):
                log.warning(
                    "apiserver unreachable; state report (%s) deferred to "
                    "the intent journal", state_value,
                )
                return
            raise

    def _journal_begin(self, kind: str, **fields) -> str | None:
        if self.intents is None:
            return None
        try:
            return self.intents.begin(kind, **fields)
        except intent_mod.JournalError as e:
            log.warning(
                "intent journal unavailable; %s runs unjournaled: %s",
                kind, e,
            )
            return None

    def _journal_mark(self, txn: str | None, phase: str) -> None:
        if txn is None or self.intents is None:
            return
        try:
            self.intents.mark(txn, phase)
        except intent_mod.JournalError as e:
            log.warning("intent journal mark failed: %s", e)

    def _journal_close(self, txn: str | None, ok: bool, **fields) -> None:
        if txn is None or self.intents is None:
            return
        try:
            if ok:
                self.intents.commit(txn, **fields)
            else:
                self.intents.abort(txn, **fields)
        except intent_mod.JournalError as e:
            log.warning("intent journal close failed: %s", e)

    def with_default(self, label_value: str | None) -> str:
        """Absent/empty desired label means the configured default
        (reference main.py:686-691)."""
        if not label_value:
            log.info("no %s label; defaulting to %s", CC_MODE_LABEL, self.default_mode)
            return self.default_mode
        return canonical_mode(label_value)

    def _note_rollout_trace(self, labels: dict) -> None:
        """Remember the orchestrator trace identity riding in the
        desired-mode patch (tentpole: cross-process stitching). Garbled
        values parse to None — a stitching hint must never fail a
        reconcile."""
        self._rollout_trace_parent = trace_mod.parse_parent(
            labels.get(labels_mod.ROLLOUT_TRACE_LABEL)
        )

    def get_node_cc_mode_label(self) -> tuple[str | None, str]:
        """Read the desired-mode label and the node's resourceVersion.

        Apiserver errors propagate — at startup that is fatal by design
        (reference main.py:596-598, crash-as-retry)."""
        node = self.api.get_node(self.node_name)
        labels = node_labels(node)
        self._note_rollout_trace(labels)
        return labels.get(CC_MODE_LABEL), resource_version(node)

    def create_readiness_file(self) -> None:
        """Touch the readiness file after the first successful apply; failures
        are non-fatal (reference main.py:66-78)."""
        try:
            os.makedirs(os.path.dirname(self.readiness_file), exist_ok=True)
            with open(self.readiness_file, "w", encoding="utf-8"):
                pass
            log.info("created readiness file %s", self.readiness_file)
        except OSError as e:
            log.warning("could not create readiness file %s: %s", self.readiness_file, e)

    # ------------------------------------------------------------------
    # Mode application (reference call stack 3.2/3.3)
    # ------------------------------------------------------------------

    def set_cc_mode(self, mode: str) -> bool:
        self.reconciling = True
        self.retryable_failure = True
        if self.intents is not None:
            # Boot-time local truth: a restart that cannot reach the
            # apiserver serves this journaled desired mode instead of
            # crash-looping with no record of what it was converging on.
            try:
                self.intents.note_desired(canonical_mode(mode))
            except intent_mod.JournalError as e:
                log.warning("could not journal desired mode: %s", e)
        try:
            # One reconcile = one trace: every phase span, drain step,
            # barrier wait and log line below nests under this root —
            # and when the desired mode came from a rolling orchestrator
            # the root itself adopts the ROLLOUT trace as its remote
            # parent (labels.ROLLOUT_TRACE_LABEL), so the orchestrator's
            # /tracez renders `ctl rollout` and this node's
            # drain/reset/smoke as one causal tree.
            with trace_mod.root_span(
                "reconcile", journal=self.journal,
                parent=self._rollout_trace_parent,
                mode=mode, node=self.node_name,
            ) as sp:
                ok = self._set_cc_mode(mode)
                sp.set_attribute("ok", ok)
                if not ok:
                    sp.status = trace_mod.STATUS_ERROR
                # Republish this reconcile's trace id on the node so
                # `ctl status` can surface a TRACE column (the event
                # annotation alone dies with the event's TTL).
                self._publish_trace_annotation(sp.trace_id)
                if ok:
                    # A reconcile republishes the quote under a fresh
                    # self-chosen nonce, so any verifier challenge this
                    # agent answered earlier is no longer reflected in
                    # the published evidence — forget the answer marker
                    # so a still-outstanding challenge is re-answered on
                    # the next watch event.
                    self._answered_challenge_nonce = None
                    # A consumed handoff is complete once any reconcile
                    # succeeds: the handed-off flip either committed or
                    # was superseded by a newer desired mode.
                    if not self._prestage_held:
                        # A prestage HOLD is not a completed flip: the
                        # handoff record and prestage annotations must
                        # survive it untouched.
                        self._retire_handoff()
                        # Prestage housekeeping: a desired write matching
                        # the pre-staged mode consumes the request; one
                        # that moved past it clears the stale record.
                        self._consume_prestage(mode)
                return ok
        finally:
            self.reconciling = False

    def _set_cc_mode(self, mode: str) -> bool:
        mode = canonical_mode(mode)
        self._prestage_held = False
        if self.remediation is not None and self.remediation.quarantined:
            # Containment: a quarantined node stops hammering known-bad
            # hardware. The reconcile is deferred (slow re-check cadence);
            # probation or `tpu-cc-ctl unquarantine` releases it and the
            # pending retry then re-applies the desired mode.
            log.warning(
                "node is quarantined; deferring reconcile of mode %s "
                "(probation or operator lift releases it)", mode,
            )
            self.retryable_failure = False
            return False
        if mode not in VALID_MODES:
            # A typo'd label is as stable as unsupported hardware: report
            # failed with a reason (the reference refuses silently, leaving
            # no outward signal) and retry only at the slow cadence.
            log.error(
                "invalid CC mode %r (valid: %s) — refusing to act", mode, VALID_MODES
            )
            self.retryable_failure = False
            self._record_failure("invalid-mode")
            self._report_state(STATE_FAILED, reason="invalid-mode")
            self._emit_node_event(
                "Warning", "CCModeInvalid", f"invalid desired CC mode {mode!r}"
            )
            return False
        if not self.host_cc_capable and mode != MODE_OFF:
            # Warning only; the backend/attestation will produce the hard
            # failure (reference main.py:224-225).
            log.warning(
                "host/VM is not CC-capable but mode %s requested; "
                "attestation will likely fail", mode,
            )

        try:
            topo = self.backend.discover()
        except TpuError as e:
            log.error("TPU discovery failed: %s", e)
            self._record_failure("discovery-failed")
            self._report_state(STATE_FAILED, reason="discovery-failed")
            self._emit_node_event(
                "Warning", "CCModeFailed", f"TPU discovery failed: {e}"
            )
            return False

        if not topo.chips:
            log.info("no TPU chips on this node; nothing to do")
            return True

        try:
            if mode == MODE_SLICE:
                chips = self._slice_mode_chips(topo)
            else:
                chips = self._cc_mode_chips(topo, mode)
        except ModeUnsupported as e:
            # Fail SOFT: a mislabeled node (e.g. slice mode on single-host
            # hardware) reports failed + reason and keeps watching — a crash
            # loop can't be fixed by a label edit the agent never sees.
            # Crash-as-retry stays only for mixed capability (reference
            # main.py:237-240), where a restart can genuinely re-enumerate.
            log.error("mode %s unsupported on this node: %s", mode, e)
            self.retryable_failure = False  # only a label/pool edit helps
            self._record_failure(e.reason)
            self._report_state(STATE_FAILED, reason=e.reason)
            self._emit_node_event(
                "Warning", "CCModeUnsupported",
                f"mode {mode} unsupported on this node: {e}",
            )
            return False
        if chips is None:  # nothing to reconfigure; state already reported
            return True

        if self._prestage_hold(mode, chips):
            # Deliberate desired!=state: the node pre-staged a mode for
            # an upcoming rollout wave and holds it. Not a failure, not
            # drift — the wave's desired write (or a deleted request
            # annotation) resolves it.
            self._prestage_held = True
            return True

        if self._mode_is_set(chips, mode):
            # Idempotent path (reference main.py:255-258) — but a restarted
            # agent must still re-attest and re-publish coordination labels:
            # slice grouping and pool attestation read them, and quotes age
            # out. A failed re-attestation falls through to the full apply.
            quote = None
            if mode != MODE_OFF:
                try:
                    nonce = attestation.fresh_nonce()
                    quote = self.backend.fetch_attestation(nonce)
                    attestation.verify_quote(
                        quote,
                        nonce,
                        expected_mode=mode,
                        expected_slice_id=topo.slice_id,
                        debug_policy=(mode == MODE_DEVTOOLS),
                        allow_fake=self.allow_fake_quotes,
                    )
                except TpuError as e:
                    log.warning(
                        "mode %s reads as set but re-attestation failed (%s); "
                        "running the full apply", mode, e,
                    )
                    quote = None
            if mode == MODE_OFF or quote is not None:
                log.info("CC mode %s already set on all %d chip(s)", mode, len(chips))
                # A crash (or apiserver failure) BETWEEN the mode landing
                # and re-admission leaves components paused; the next
                # reconcile takes this idempotent path — which skips the
                # drain/readmit bracket — so it must restore them (found
                # by the chaos soak). BEFORE the state labels: a node must
                # not advertise ready while its components are known to
                # still be paused.
                self._readmit_leftover_paused()
                self._report_state(mode)
                self._publish_coordination_labels(topo, quote)
                return True

        barrier = None
        if topo.is_multi_host:
            barrier = slicecoord.SliceBarrier(
                self.api,
                self.node_name,
                topo,
                timeout_s=self.slice_barrier_timeout_s,
                poll_interval_s=self.slice_barrier_poll_interval_s,
                informer=self._slice_peer_informer(topo),
            )
        m = self.metrics.start(mode)
        try:
            if self.evict_components:
                ok = self._apply_with_eviction(topo, chips, mode, m, barrier)
            else:
                ok = self._apply_direct(topo, chips, mode, m, barrier)
        except BaseException:
            # An escaping exception (e.g. KubeApiError mid-drain) must not be
            # recorded as a successful reconcile.
            if m.result == "pending":
                m.result = "failed"
            raise
        finally:
            m.finish(m.result if m.result != "pending" else "noop")
        if ok and barrier is not None:
            # Barrier completion AFTER re-admit: the leader's (bounded) wait
            # for peers to clear their staged markers before retiring the
            # commit marker must never keep this host's components paused —
            # only the leader's own watch loop lingers, not the drain window.
            barrier.complete(mode)
        return ok

    def _slice_peer_informer(self, topo):
        """The (lazily started, reused) informer over this node's slice
        membership selector, or None when disabled/unsupported — the
        barrier then falls back to polling listings, so a degraded cache
        can never block a commit."""
        if not self.use_slice_informer or not topo.is_multi_host:
            return None
        from tpu_cc_manager.ccmanager.informer import NodeInformer
        from tpu_cc_manager.labels import SLICE_ID_LABEL, label_safe

        selector = f"{SLICE_ID_LABEL}={label_safe(topo.slice_id)}"
        if (
            self._peer_informer is not None
            and self._peer_informer.selector == selector
        ):
            return self._peer_informer
        self._stop_peer_informer()
        try:
            self._peer_informer = NodeInformer(
                self.api, selector,
                name=f"slice-peers[{topo.slice_id}]",
            ).start()
        except KubeApiError as e:
            log.warning(
                "slice-peer informer unavailable (%s); the barrier falls "
                "back to peer listings", e,
            )
            self._peer_informer = None
        return self._peer_informer

    def _stop_peer_informer(self) -> None:
        if self._peer_informer is not None:
            self._peer_informer.stop()
            self._peer_informer = None

    def _readmit_leftover_paused(self) -> None:
        """Unpause components a previous run left paused (it died between
        committing the mode and re-admitting). ``original={}`` means the
        restore derives purely from the current label values — exactly the
        crash-recovery semantics readmit_components documents. An apiserver
        failure here propagates: the reconcile is noted failed and the
        backoff retry re-attempts the restore — reporting success over
        still-stranded components would end the retry ladder with the node
        not serving. A successful restore also retires any drain intents a
        crashed run left open in the journal — the stranding they recorded
        no longer exists."""
        evict.readmit_components(self.api, self.node_name, {})
        if self.intents is not None:
            try:
                self.intents.close_open("drain", recovered="readmitted")
            except intent_mod.JournalError as e:
                log.warning("could not close recovered drain intents: %s", e)

    def _cc_mode_chips(
        self, topo: SliceTopology, mode: str
    ) -> tuple[TpuChip, ...] | None:
        """Select chips for a non-slice mode change, with the reference's
        mixed-capability policy (main.py:232-253)."""
        cc_capable = topo.cc_capable_chips()
        if 0 < len(cc_capable) < len(topo.chips) and mode != MODE_OFF:
            # Mixed capability is unrecoverable misconfiguration: crash so the
            # DaemonSet restart surfaces it loudly (reference main.py:237-240).
            log.error(
                "node has %d CC-capable of %d chips — mixed capability cannot "
                "host mode %s; exiting (DaemonSet restart acts as retry)",
                len(cc_capable), len(topo.chips), mode,
            )
            sys.exit(1)
        if not cc_capable:
            log.info("no CC-capable chips; reporting state off")
            self._report_state(MODE_OFF)
            return None
        return topo.chips if mode == MODE_OFF else cc_capable

    def _slice_mode_chips(self, topo: SliceTopology) -> tuple[TpuChip, ...]:
        """Slice-wide CC requires every chip in the ICI domain to support it
        (the reference's all-devices-must-support-PPCIe rule, main.py:279-282).

        Divergence from the reference's sys.exit(1): unsupported hardware is
        a *stable* misconfiguration — restarting cannot change it — so it
        fails soft (failed + reason) instead of crash-looping."""
        lacking = [c for c in topo.chips if not c.slice_cc_supported]
        if lacking:
            raise ModeUnsupported(
                f"{len(lacking)} of {len(topo.chips)} chips lack slice-wide "
                f"CC support ({', '.join(c.name for c in lacking[:4])}); "
                "cannot form a slice CC domain",
                reason="slice-mode-unsupported",
            )
        return topo.chips

    def _mode_is_set(self, chips: tuple[TpuChip, ...], mode: str) -> bool:
        """Idempotency check (reference mode_is_set, main.py:428-447)."""
        try:
            return all(self.backend.query_cc_mode(c) == mode for c in chips)
        except TpuError as e:
            log.warning("query during idempotency check failed (%s); proceeding", e)
            return False

    def _apply_with_eviction(
        self, topo: SliceTopology, chips: tuple[TpuChip, ...], mode: str,
        m: metrics_mod.ReconcileMetrics,
        barrier: slicecoord.SliceBarrier | None = None,
    ) -> bool:
        """Drain, reconfigure, re-admit (reference main.py:544-578),
        pipelined (unless CC_PIPELINE_TRANSITIONS=0): staging — a pure
        staged.json write touching no workload-visible hardware — runs
        CONCURRENTLY with the pod-drain bracket. The hard orderings are
        untouched: this host's reset still waits for both the drain AND
        the stage to complete, and on multi-host slices the barrier's
        staged marker is only published AFTER the drain (the marker means
        "staged and drained"; publishing it mid-drain would let peers
        half-bounce the fabric under a strict drain that then fails), so
        no reset ever runs under undrained workloads anywhere in the
        slice.

        Re-admission runs even when the reconfigure fails, so components
        are never left paused by a failed toggle — including a strict-mode
        drain timeout, which fails the reconcile with the staging rolled
        back and no disruptive hardware touched. On the happy path the
        readmit bracket is kicked off while the smoke workload runs
        (_apply_direct), and ``readmit.finish()`` below joins it — its
        true outcome still gates the drain-intent close.

        The drain bracket is journaled intent→commit around pause/readmit:
        a crash (or SIGKILL) between the pause landing and re-admission
        leaves the intent open, and journal replay restores the paused set
        at the next boot even when the apiserver read that used to reveal
        the stranding is unavailable. The transition intent begins BEFORE
        the overlapped stage (write-ahead), so a crash anywhere in the
        drain window replays as a clean pre-reset rollback."""
        dtxn = self._journal_begin("drain", mode=mode)
        txn = None
        stage_task: _PipelineTask | None = None
        if self.pipeline_transitions:
            txn = self._begin_transition_intent(topo, chips, mode)
            stage_task = _PipelineTask(
                "stage",
                lambda: self._stage_for_pipeline(chips, mode, m, txn),
            )
        try:
            with m.phase(metrics_mod.PHASE_DRAIN):
                original = evict.evict_components(
                    self.api,
                    self.node_name,
                    self.operator_namespace,
                    timeout_s=self.eviction_timeout_s,
                    poll_interval_s=self.eviction_poll_interval_s,
                    proceed_on_timeout=not self.strict_eviction,
                    workload_ack_timeout_s=self.drain_ack_timeout_s,
                )
        except evict.EvictionTimeout as e:
            log.error("strict eviction failed: %s — not touching hardware", e)
            self._unwind_pipelined_stage(stage_task, chips, txn,
                                         reason="drain-timeout")
            txn = None
            m.result = "failed"
            self._record_failure("drain-timeout")
            self._emit_node_event(
                "Warning", "CCModeDrainTimeout",
                f"strict eviction timed out before mode {mode}: {e}",
            )
            try:
                self._report_state(STATE_FAILED, reason="drain-timeout")
            finally:
                # Re-admit even if the state-label patch itself fails —
                # components must never stay paused behind a failed toggle.
                with m.phase(metrics_mod.PHASE_READMIT):
                    evict.readmit_components(self.api, self.node_name, e.original)
                self._journal_close(dtxn, ok=True, outcome="drain-timeout")
            return False
        except BaseException:
            # Any other exception escaping the drain (e.g. a transport
            # error during the pod wait, AFTER the pause patch landed)
            # leaves the drain intent OPEN on purpose: components may
            # genuinely be paused, and replay's recovery readmit is a
            # no-op when they are not. The overlapped stage thread must
            # not outlive the reconcile, and the open transition intent
            # (phase begun/staged) replays as a clean rollback.
            if stage_task is not None:
                stage_err = stage_task.join_quiet()
                if stage_err is not None:
                    log.warning(
                        "overlapped stage also failed during the aborted "
                        "drain: %s", stage_err,
                    )
            with self._transition_lock:
                self._inflight_transition = None
            raise
        # Re-admission is kicked off by _apply_direct while the smoke
        # workload runs (readmit ∥ smoke); finish() below joins it — or
        # runs it synchronously when the pipeline never got that far.
        readmit = _ReadmitOnce(
            lambda: self._readmit_bracket(m, original),
            on_start=lambda: self._journal_mark(
                dtxn, intent_mod.PHASE_READMIT
            ),
        )
        try:
            return self._apply_direct(
                topo, chips, mode, m, barrier,
                txn=txn, stage_task=stage_task, readmit=readmit,
            )
        finally:
            readmit.finish()
            # Only after a SUCCESSFUL readmit (a readmit aborted by an
            # apiserver error must leave the intent open for replay); the
            # restore covered any stranding, so older leftover drain
            # intents retire with this one.
            self._journal_close(dtxn, ok=True)
            if self.intents is not None:
                try:
                    self.intents.close_open("drain", recovered="readmitted")
                except intent_mod.JournalError as err:
                    log.warning("could not close drain intents: %s", err)

    def _begin_transition_intent(
        self, topo: SliceTopology, chips: tuple[TpuChip, ...], mode: str,
    ) -> str | None:
        """Write-ahead intent: the journal record lands (fsync'd) BEFORE
        the first hardware-effecting step, so a crash anywhere in the
        pipeline restarts with a local record of exactly what was in
        flight — phase marks tell replay whether the disruptive reset had
        begun (roll back) or may have landed (ask the hardware). Also
        publishes the in-flight record the preemption monitor thread
        hands off to a replacement node (handle_preemption_notice)."""
        txn = self._journal_begin(
            "transition", mode=mode, chips=[c.index for c in chips],
        )
        with self._transition_lock:
            self._inflight_transition = {
                "mode": mode,
                "chips": [c.index for c in chips],
                "phase": intent_mod.PHASE_BEGUN,
                "slice_id": topo.slice_id,
                "multi_host": topo.is_multi_host,
            }
        return txn

    def _readmit_bracket(self, m: metrics_mod.ReconcileMetrics,
                         original: dict) -> None:
        with m.phase(metrics_mod.PHASE_READMIT):
            evict.readmit_components(self.api, self.node_name, original)

    def _stage_for_pipeline(
        self, chips: tuple[TpuChip, ...], mode: str,
        m: metrics_mod.ReconcileMetrics,
        txn: str | None,
    ) -> None:
        """The overlapped half of stage-during-drain: stage the chips —
        a pure staged.json write, no workload-visible hardware.

        Deliberately NOT overlapped: the slice barrier's staged-marker
        publication. The marker means "this host is staged AND DRAINED";
        publishing it mid-drain would let the leader commit — and peers
        reset, disrupting the whole ICI fabric — while this host's pods
        are still draining (or while a strict drain is about to fail
        without ever touching hardware). It is published at drain-join
        in _apply_direct, exactly as honest as before."""
        with m.phase(metrics_mod.PHASE_STAGE):
            self.backend.stage_cc_mode(chips, mode)
        self._journal_mark(txn, intent_mod.PHASE_STAGED)
        with self._transition_lock:
            if self._inflight_transition is not None:
                self._inflight_transition["phase"] = intent_mod.PHASE_STAGED

    def _unwind_pipelined_stage(
        self, stage_task: _PipelineTask | None,
        chips: tuple[TpuChip, ...],
        txn: str | None,
        reason: str,
    ) -> None:
        """Roll an overlapped stage back out on a pre-hardware failure
        (strict drain timeout): nothing disruptive ran — and no barrier
        marker was published (publication waits for the drain) — so the
        clean exit is clear_staged + an aborted intent, the same shape
        journal replay produces for a pre-reset crash."""
        if stage_task is None:
            self._journal_close(txn, ok=False, reason=reason)
            with self._transition_lock:
                self._inflight_transition = None
            return
        stage_err = stage_task.join_quiet()
        if stage_err is not None:
            log.warning("overlapped stage failed (%s); rolling back anyway",
                        stage_err)
        try:
            self.backend.clear_staged(chips)
        except TpuError as e:
            log.warning("could not clear staged mode during unwind: %s", e)
        self._journal_close(txn, ok=False, reason=reason)
        with self._transition_lock:
            self._inflight_transition = None

    def _apply_direct(
        self, topo: SliceTopology, chips: tuple[TpuChip, ...], mode: str,
        m: metrics_mod.ReconcileMetrics,
        barrier: slicecoord.SliceBarrier | None = None,
        txn: str | None = None,
        stage_task: _PipelineTask | None = None,
        readmit: _ReadmitOnce | None = None,
    ) -> bool:
        """The phased hardware transition (reference main.py:449-542,
        restructured: slice atomicity is structural in the backend contract,
        and verify is upgraded with attestation + smoke), pipelined where
        the contract allows:

        - ``stage_task`` (from _apply_with_eviction) means the stage (and
          multi-host staged publication) already ran overlapped with the
          drain; it is joined here — strictly before any barrier wait or
          reset — so stage/publish failures surface exactly like serial
          ones and a modeled SIGKILL in the overlapped step unwinds as a
          crash.
        - attestation prep (measured-file hashing) overlaps wait_ready.
        - ``readmit`` (when provided) is kicked off right before the smoke
          workload: re-admission is pure apiserver label writes and the
          hardware transition is already committed and attested by then.
        - the attestation-digest fast path (CC_SMOKE_DIGEST_FAST_PATH)
          skips the full smoke when the verified runtime digest is
          unchanged since the last full-smoke-verified flip.

        On a multi-host slice, ANY mode change disrupts the whole ICI
        domain, so the reset is gated behind the slice-wide commit barrier
        (``barrier``, built by set_cc_mode): no host resets before every
        host of the slice is staged — the cross-host generalization of the
        reference's PPCIe stage-all/reset-all fabric atomicity
        (main.py:362-368) — and never before its OWN drain completed.
        Barrier COMPLETION (marker cleanup, the leader's bounded wait for
        peers) happens in set_cc_mode after re-admission, so it never
        extends the drain window."""
        if txn is None:
            # The pipelined evict path began the intent before the drain;
            # the serial/direct path begins it here.
            txn = self._begin_transition_intent(topo, chips, mode)
        warmup = None
        try:
            if stage_task is not None:
                # Joined strictly before the staged publication, the
                # barrier wait and the reset: the drain has already
                # completed by the time we are called, so the published
                # marker's "staged and drained" claim is true.
                stage_task.join()
            else:
                with m.phase(metrics_mod.PHASE_STAGE):
                    self.backend.stage_cc_mode(chips, mode)
                self._journal_mark(txn, intent_mod.PHASE_STAGED)
                with self._transition_lock:
                    self._inflight_transition["phase"] = intent_mod.PHASE_STAGED
            if barrier is not None:
                with m.phase(metrics_mod.PHASE_BARRIER):
                    barrier.publish_staged(mode)
                    barrier.await_commit(mode)
            self._journal_mark(txn, intent_mod.PHASE_RESET)
            with self._transition_lock:
                self._inflight_transition["phase"] = intent_mod.PHASE_RESET
            with m.phase(metrics_mod.PHASE_RESET):
                self.backend.reset(chips)
            # Attestation prep (tpuvm: hashing an O(100 MB) libtpu into
            # the measured-file memo) needs nothing from the post-reset
            # runtime — overlap it with the boot wait. Advisory: a prep
            # failure is swallowed; fetch_attestation re-does the work.
            prep_task = None
            if self.pipeline_transitions and mode != MODE_OFF:
                prep_task = _PipelineTask("attest-prep", self._attest_prep)
            # Smoke warmup ∥ wait_ready: the smoke subprocess starts NOW
            # in compile-only mode (dispatch gated), so the boot wait
            # absorbs its interpreter-start + import + compile span. The
            # gate is released only at the smoke phase below — after the
            # runtime is verifiably ready and attestation passed — and
            # every failure path cancels the child instead of releasing.
            run_smoke = bool(self.smoke_workload) and self.smoke_workload != "none"
            if run_smoke and self.pipeline_transitions and self.smoke_warmup:
                warmup = self._start_smoke_warmup()
            try:
                with m.phase(metrics_mod.PHASE_WAIT_READY):
                    self.backend.wait_ready(chips, self.ready_timeout_s)
            finally:
                if prep_task is not None:
                    prep_err = prep_task.join_quiet()
                    if prep_err is not None:
                        log.debug("attestation prep failed (advisory): %s",
                                  prep_err)
            # Verify 1: committed mode matches (reference main.py:524-528).
            for chip in chips:
                got = self.backend.query_cc_mode(chip)
                if got != mode:
                    raise TpuError(
                        f"verification failed on {chip.name}: "
                        f"wanted {mode}, device reports {got}"
                    )
            # The hardware transition is now fact: commit the intent before
            # the (non-hardware) attest/smoke verifies — their failure
            # labels the node failed but must not make replay re-reset
            # chips that verifiably hold the mode.
            self._journal_close(txn, ok=True)
            txn = None
            # Verify 2: attestation (new; skipped for plain 'off').
            quote = None
            if mode != MODE_OFF:
                with m.phase(metrics_mod.PHASE_ATTEST):
                    nonce = attestation.fresh_nonce()
                    quote = self.backend.fetch_attestation(nonce)
                    attestation.verify_quote(
                        quote,
                        nonce,
                        expected_mode=mode,
                        expected_slice_id=topo.slice_id,
                        debug_policy=(mode == MODE_DEVTOOLS),
                        allow_fake=self.allow_fake_quotes,
                    )
            # Verify 3: end-to-end JAX smoke workload (new), with the
            # attestation-digest fast path (env-gated, default off): a
            # flip landing on the exact runtime digest the last FULL
            # smoke verified may skip the workload — attest-only verify.
            fastpath_hit = False
            if run_smoke and quote is not None and self.smoke_digest_fastpath:
                fastpath_hit = self._smoke_fastpath_check(quote)
            if readmit is not None and self.pipeline_transitions:
                # Safe-to-release point: every chip verifiably holds the
                # committed mode, the intent is closed, and attestation
                # passed. Re-admission (pure apiserver label writes) runs
                # while the smoke compiles/executes; its true outcome is
                # joined by the owner's finish() before the drain intent
                # closes.
                readmit.start_async()
            if warmup is not None and warmup.died_during_warmup():
                # The child died BEFORE any release — a warmup
                # infrastructure failure (e.g. client init against the
                # mid-boot runtime), not a smoke verdict. The serial
                # smoke below runs against the now-ready, attested
                # runtime, so the flip is judged by the same evidence
                # the pre-warmup pipeline used.
                log.warning(
                    "smoke warmup child died before release; falling "
                    "back to the synchronous smoke"
                )
                warmup.cancel("died-during-warmup")
                warmup = None
            if run_smoke and not fastpath_hit:
                with m.phase(metrics_mod.PHASE_SMOKE):
                    if warmup is not None:
                        # Dispatch release point: ready + attested, by
                        # construction of everything above this line.
                        result = warmup.release_and_result()
                        warmup = None
                        log.info(
                            "smoke warmup overlapped %.2fs of compile "
                            "with the boot wait (dispatch %.2fs)",
                            result.get("warmup_overlap_s") or 0.0,
                            result.get("warmup_dispatch_s") or 0.0,
                        )
                    else:
                        self._run_smoke(self.smoke_workload)
                if quote is not None:
                    self._store_verified_digest(quote)
            elif warmup is not None and fastpath_hit:
                # The digest fast path decided the full smoke is not
                # needed; the warmed child must never dispatch.
                warmup.cancel("digest-fastpath")
                warmup = None
        except Exception as e:  # noqa: BLE001 - reference parity:
            # any failure labels the node 'failed' and keeps the loop alive
            # (main.py:531-538). BaseExceptions (sys.exit, a modeled
            # SIGKILL) bypass this handler and leave the intent OPEN —
            # exactly the crash record replay recovers from.
            self._journal_close(txn, ok=False, reason=self._failure_reason(e))
            txn = None
            log.error("CC mode change to %s failed: %s", mode, e, exc_info=True)
            if barrier is not None:
                # This host is about to re-admit components, so "staged and
                # drained" no longer describes it: withdraw from the barrier.
                barrier.abort()
            reason = self._failure_reason(e)
            self._record_failure(reason)
            self._report_state(STATE_FAILED, reason=reason)
            self._emit_node_event(
                "Warning", "CCModeFailed", f"CC mode change to {mode} failed: {e}"
            )
            m.result = "failed"
            return False
        finally:
            # The hardware pipeline is over (committed, failed, or a
            # modeled crash unwinding) — there is no transition left to
            # hand off. A warmup child that was never consumed must not
            # dispatch (failure paths, unwinding): kill it. (On a REAL
            # SIGKILL no finally runs; the child covers that itself via
            # the gate's parent-pid watch and exits instead of orphaning.)
            if warmup is not None:
                try:
                    warmup.cancel("pipeline-unwound")
                except Exception as e:  # noqa: BLE001 - never mask the cause
                    log.warning("could not cancel the smoke warmup: %s", e)
            with self._transition_lock:
                self._inflight_transition = None
        self._report_state(mode)
        # The publish patch below also withdraws this host's staged marker
        # (it is no longer mid-transition); the leader's commit-marker
        # retirement waits until set_cc_mode's post-readmit completion.
        self._publish_coordination_labels(topo, quote)
        m.result = "ok"
        log.info("CC mode %s applied and verified on %d chip(s)", mode, len(chips))
        self._emit_node_event(
            "Normal", "CCModeApplied",
            f"CC mode {mode} applied and verified on {len(chips)} chip(s)",
        )
        return True

    @staticmethod
    def _failure_reason(e: Exception) -> str:
        """Machine-readable failed.reason for an apply/verify failure.

        Every ``failed`` state carries a reason (the stateful property
        test's invariant — an operator staring at ``failed`` with no
        reason has only the logs, which a label watcher never sees)."""
        from tpu_cc_manager.smoke.runner import SmokeError

        if isinstance(e, slicecoord.BarrierFenced):
            return "barrier-fenced"
        if isinstance(e, slicecoord.BarrierTimeout):
            return "barrier-timeout"
        if isinstance(e, attestation.AttestationError):
            return "attestation-failed"
        if isinstance(e, SmokeError):
            return "smoke-failed"
        if isinstance(e, KubeApiError):
            return "apiserver-error"
        return "apply-failed"

    def _publish_coordination_labels(self, topo: SliceTopology, quote) -> None:
        """Advertise slice membership + attestation digest on the node so the
        rolling orchestrator can group hosts by slice and the multi-slice
        verifier can compare runtime digests (ccmanager/rolling.py,
        ccmanager/multislice.py). Best-effort: coordination metadata must
        never fail a reconcile."""
        try:
            from tpu_cc_manager.ccmanager import multislice
            from tpu_cc_manager.ccmanager.rolling import SLICE_ID_LABEL

            # One merge-patch for slice id + quote labels (or None-clears
            # when mode off): a single apiserver round trip, and no window
            # where the slice label is visible with a stale quote. On
            # multi-host topologies the same patch retires the slice staged
            # marker — the mode is set, so "mid-transition" no longer
            # describes this host (covers both the normal apply path and a
            # marker left by a crash between barrier commit and clear,
            # which the idempotent path would otherwise never clean up).
            patch = {SLICE_ID_LABEL: label_safe(topo.slice_id)}
            patch.update(multislice.quote_label_patch(quote))
            if topo.is_multi_host:
                # Best-effort, like the rest of this patch (clear_staged
                # always was — slicecoord.py:197 swallows KubeApiError). A
                # clear lost to an outage is retried by barrier.complete()
                # on the apply path and cleared at the next barrier entry
                # otherwise; followers never act on a staged marker without
                # re-verifying full staging.
                patch[slicecoord.SLICE_STAGED_LABEL] = None
                patch[slicecoord.SLICE_STAGED_GEN_LABEL] = None
            self.api.patch_node_labels(self.node_name, patch)
            # The full signed quote (or a clear when there is none) rides
            # in an annotation so PEERS can re-verify the signature instead
            # of trusting the digest labels (multislice.py trust model).
            multislice.publish_quote_annotation(
                self.api, self.node_name, quote
            )
            if quote is not None:
                log.info(
                    "published attestation for %s: digest=%s mode=%s",
                    self.node_name,
                    patch[f"{multislice.QUOTE_ANNOTATION}.digest"],
                    quote.mode,
                )
        except Exception as e:  # noqa: BLE001 - advisory metadata only
            log.warning("could not publish coordination labels: %s", e)

    def _maybe_answer_challenge(self, node: dict) -> None:
        """Answer an outstanding verifier challenge (multislice.py,
        VERDICT weak #5): re-quote bound to the verifier's nonce and
        republish, giving pool attestation peer-chosen-challenge
        freshness — a replayed old quote cannot carry a nonce the
        verifier only just minted. Best-effort like all coordination
        metadata: an un-answerable challenge (device busy, apiserver
        flake) is logged and re-attempted on the next watch event, and
        verification fails loudly on its own timeout."""
        try:
            from tpu_cc_manager.ccmanager import multislice
            from tpu_cc_manager.labels import CC_MODE_STATE_LABEL

            nonce = multislice.challenge_nonce_of(node)
            if nonce is None or nonce == self._answered_challenge_nonce:
                return
            if self.reconciling:
                return  # the reconcile republishes; answer on the next event
            state = canonical_mode(
                node_labels(node).get(CC_MODE_STATE_LABEL) or ""
            )
            if state not in VALID_MODES or state == MODE_OFF:
                # No committed CC mode -> no quote to re-bind. Remember
                # the nonce so an off node doesn't re-log every event;
                # verification already treats the node as unattested.
                log.info(
                    "challenge %s ignored: no attested mode on this node "
                    "(state=%r)", nonce[:8], state,
                )
                self._answered_challenge_nonce = nonce
                return
            topo = self.backend.discover()
            quote = self.backend.fetch_attestation(nonce)
            attestation.verify_quote(
                quote,
                nonce,
                expected_mode=state,
                expected_slice_id=topo.slice_id,
                debug_policy=(state == MODE_DEVTOOLS),
                allow_fake=self.allow_fake_quotes,
            )
            # strict: a swallowed annotation-patch failure must NOT mark
            # the challenge answered (the verifier would time out on a
            # healthy node that never retries) — it raises into the
            # except below and the next watch event re-answers.
            multislice.publish_quote(
                self.api, self.node_name, quote, strict=True
            )
            self._answered_challenge_nonce = nonce
            # Retire the answered challenge so it cannot re-arm after the
            # next reconcile republishes a self-nonce quote — but only if
            # it still holds OUR nonce: a newer challenge issued during
            # the quote fetch must stay for the next event to answer.
            multislice.retire_answered_challenge(
                self.api, self.node_name, nonce
            )
            log.info(
                "answered verifier challenge %s… with a re-quote bound to "
                "it (mode=%s)", nonce[:8], state,
            )
        except Exception as e:  # noqa: BLE001 - advisory; next event retries
            log.warning("could not answer verifier challenge: %s", e)

    def _start_smoke_warmup(self):
        """Spawn the smoke subprocess in compile-only warmup mode (the
        dispatch gate armed), to run concurrently with wait_ready.

        Returns a handle with ``release_and_result()`` / ``cancel()`` —
        the :class:`~tpu_cc_manager.smoke.runner.SmokeWarmup` contract —
        or None when the warmup can't apply: an injected smoke_runner
        with no matching warmup factory (tests, custom harnesses) keeps
        today's synchronous smoke, and a spawn failure degrades the same
        way (advisory: the serial path still verifies end to end)."""
        factory = self.smoke_warmup_factory
        if factory is None:
            if self.smoke_runner is not None:
                return None
            from tpu_cc_manager.smoke.runner import SmokeWarmup

            factory = SmokeWarmup
        try:
            return factory(self.smoke_workload)
        except Exception as e:  # noqa: BLE001 - warmup is an optimization
            log.warning(
                "smoke warmup spawn failed (falling back to the "
                "synchronous smoke): %s", e,
            )
            return None

    def _run_smoke(self, workload: str) -> dict:
        if self.smoke_runner is not None:
            return self.smoke_runner(workload)
        from tpu_cc_manager.smoke.runner import run_workload_subprocess

        return run_workload_subprocess(workload)

    def _attest_prep(self) -> None:
        """Overlapped attestation prep (runs during wait_ready)."""
        with trace_mod.span("attest.prep"):
            self.backend.prepare_attestation()

    # ------------------------------------------------------------------
    # Attestation-digest smoke fast path (CC_SMOKE_DIGEST_FAST_PATH)
    # ------------------------------------------------------------------

    def _digest_store_path(self) -> str | None:
        if not self._state_dir:
            return None
        return os.path.join(self._state_dir, "verified_digest.json")

    def _load_verified_digest(self) -> dict | None:
        path = self._digest_store_path()
        if path is None:
            return None
        try:
            with open(path, "r", encoding="utf-8") as f:
                record = json.load(f)
        except FileNotFoundError:
            return None
        except (OSError, ValueError) as e:
            log.warning("unreadable verified-digest record %s: %s", path, e)
            return None
        return record if isinstance(record, dict) else None

    def _store_verified_digest(self, quote) -> None:
        """Persist the runtime measurement digest a FULL smoke just
        verified (atomic write in the backend state dir). Best-effort:
        the fast path degrades to 'cold' (full smoke every flip) when it
        cannot persist — never the other way around."""
        path = self._digest_store_path()
        if path is None:
            return
        record = {
            "digest": attestation.quote_digest(quote),
            "mode": quote.mode,
            "ts": round(time.time(), 3),
        }
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(record, f)
            os.replace(tmp, path)
        except OSError as e:
            log.warning("could not persist the verified digest: %s", e)

    def _smoke_fastpath_check(self, quote) -> bool:
        """Whether this flip may skip the full smoke: True only when the
        quote's measurement digest equals the digest the last FULL smoke
        verified (same mode included — the digest binds cc_mode, but the
        record is double-checked so a hand-edited file cannot cross
        modes). Any change — or no record at all — falls through to the
        full smoke. Counted per outcome in tpu_cc_smoke_fastpath_total."""
        digest = attestation.quote_digest(quote)
        stored = self._load_verified_digest()
        if stored is None:
            outcome, hit = "cold", False
        elif (
            stored.get("digest") == digest
            and stored.get("mode") == quote.mode
        ):
            outcome, hit = "hit", True
        else:
            outcome, hit = "miss", False
        self.metrics.record_smoke_fastpath(outcome)
        with trace_mod.span(
            "smoke.fastpath", outcome=outcome, digest=digest[:12],
        ):
            if hit:
                log.info(
                    "smoke fast path: runtime digest %s… unchanged since "
                    "the last full-smoke verify; skipping the %s workload "
                    "(attest-only verify)", digest[:12], self.smoke_workload,
                )
            else:
                log.info(
                    "smoke fast path: %s (digest %s…); running the full "
                    "smoke", outcome, digest[:12],
                )
        return hit

    # ------------------------------------------------------------------
    # Intent-journal boot recovery (before the first apiserver read)
    # ------------------------------------------------------------------

    def recover_from_journal(self) -> None:
        """Replay the intent journal and resolve whatever a crash left in
        flight — from LOCAL truth (journal + hardware), before the first
        apiserver read, so recovery works identically whether the control
        plane is back or still dark.

        Per open transition intent: if every journaled chip already
        reports the intended mode, the reset landed before the crash —
        the intent completes with NO second reset, and the state report
        is queued (deferred while dark). If the crash hit before the
        reset phase, nothing disruptive ran: the staging is rolled back
        and the intent aborted. If the reset had begun but the hardware
        doesn't hold the mode, the reset provably never committed (the
        tpuvm backend's pending markers keep reporting ``resetting``) —
        the intent aborts and the normal reconcile re-applies: each chip
        is reset at most once across the crash, never twice.

        Open drain intents get their components re-admitted when the
        apiserver answers; while dark they stay open and the first
        reconcile's readmit retires them.

        A journal that fails closed (mid-file corruption) feeds the
        remediation ladder instead of guessing at half-applied state."""
        if self.intents is None:
            return
        try:
            replayed = self.intents.replay()
        except intent_mod.JournalCorrupt as e:
            log.error("intent journal failed closed: %s", e)
            self.metrics.record_journal_replay("failed-closed")
            self.last_failure_reason = "journal-corrupt"
            if self.remediation is not None:
                try:
                    self.remediation.note_failure("journal-corrupt")
                except Exception as err:  # noqa: BLE001 - ladder is advisory
                    log.warning("could not feed remediation ladder: %s", err)
            return
        transitions = self.intents.open_intents("transition")
        drains = self.intents.open_intents("drain")
        remediations = self.intents.open_intents(intent_mod.KIND_REMEDIATION)
        if replayed.records and not transitions and not drains and not remediations:
            self.metrics.record_journal_replay("clean")
        for intent in remediations:
            # A crash mid-remediation-rung: the backend's pending markers
            # already force a clean re-apply if the reset never committed,
            # and the ladder state is persisted in the node annotation —
            # close the intent and let the normal reconcile re-drive.
            self._journal_close(
                intent["txn"], ok=False, recovered="remediation-interrupted"
            )
            self.metrics.record_journal_replay("rolled-back")
            log.warning(
                "journal replay: remediation %s (%s) was interrupted; the "
                "ladder re-drives from its persisted annotation",
                intent["txn"], intent.get("op"),
            )
        for intent in transitions:
            self._recover_transition(intent)
        if drains:
            # Stranded paused components from a crashed drain bracket:
            # restore them now if the apiserver answers; otherwise the
            # intents stay open and the first post-reconnect reconcile's
            # readmit retires them.
            try:
                self._readmit_leftover_paused()
                log.info(
                    "journal replay restored components from %d open drain "
                    "intent(s)", len(drains),
                )
            except KubeApiError as e:
                self._note_api_err(e)
                log.warning(
                    "apiserver unreachable; %d open drain intent(s) kept "
                    "for the first post-reconnect reconcile: %s",
                    len(drains), e,
                )

    def _recover_transition(self, intent: dict) -> None:
        mode = canonical_mode(str(intent.get("mode") or ""))
        txn = intent["txn"]
        phase = intent.get("phase")
        try:
            topo = self.backend.discover()
        except TpuError as e:
            log.error(
                "journal replay cannot resolve %s (discovery failed: %s); "
                "intent stays open for the next restart", txn, e,
            )
            self.metrics.record_journal_replay("failed-closed")
            return
        by_index = {c.index: c for c in topo.chips}
        chips = tuple(
            by_index[i] for i in (intent.get("chips") or []) if i in by_index
        )
        committed = bool(chips) and self._mode_is_set(chips, mode)
        if committed:
            log.info(
                "journal replay: transition %s to %s already committed on "
                "the hardware; completing without a second reset", txn, mode,
            )
            self._journal_close(txn, ok=True, recovered="hardware-committed")
            self.metrics.record_journal_replay("completed")
            if not self.intents.open_intents("drain"):
                # Queue the truthful state report (deferred while dark);
                # with a drain still open the first reconcile readmits
                # BEFORE reporting — a node must not advertise ready over
                # known-stranded components.
                try:
                    self._report_state(mode, force_defer=True)
                except KubeApiError as e:
                    log.warning(
                        "recovered state report failed (%s); the first "
                        "reconcile re-reports", e,
                    )
            return
        if phase in (intent_mod.PHASE_BEGUN, intent_mod.PHASE_STAGED, None):
            # The disruptive reset never started: roll the staging back.
            try:
                self.backend.clear_staged(chips)
            except TpuError as e:
                log.warning("could not clear staged mode during replay: %s", e)
            self._journal_close(txn, ok=False, recovered="rolled-back")
            self.metrics.record_journal_replay("rolled-back")
            log.info(
                "journal replay: transition %s to %s rolled back "
                "(crash before reset; nothing disruptive ran)", txn, mode,
            )
        else:
            # Reset begun but the mode never landed: the backend's own
            # crash markers (pending.json → 'resetting') already force the
            # full re-apply; close the intent so it isn't re-judged.
            self._journal_close(txn, ok=False, recovered="reset-incomplete")
            self.metrics.record_journal_replay("rolled-back")
            log.warning(
                "journal replay: transition %s to %s was interrupted "
                "mid-reset and did not commit; the reconcile will re-apply",
                txn, mode,
            )

    # ------------------------------------------------------------------
    # Preemption fast-drain + handoff (spot/preemptible nodes)
    # ------------------------------------------------------------------

    def handle_preemption_notice(self) -> str:
        """React to a platform preemption notice inside the hard
        termination deadline (CC_PREEMPTION_DEADLINE_S ≪ the 300 s drain
        budget), in strict priority order:

        1. **fast drain** — workload checkpoint handshake first
           (checkpoint-before-pause; the training job's unsaved state is
           the one thing the kill destroys for good), then component
           eviction compressed into the remaining budget, proceeding on
           timeout (the VM dies at the deadline either way);
        2. **handoff publish** — the in-flight transition (if any) is
           journaled as a ``handoff`` intent AND mirrored to the node's
           handoff annotation, so the replacement node — fresh disk, no
           journal — resumes the flip instead of rediscovering it;
        3. **slice fence** — on a multi-host slice the fencing generation
           is bumped, so peers mid-barrier abort fast (BarrierFenced)
           instead of burning their barrier deadline on the departing
           host's staged marker.

        Idempotent per process (the platform signal is level-triggered;
        one fast drain per VM lifetime). Returns the recorded outcome:
        ``handoff`` / ``clean`` / ``handoff-failed`` / ``duplicate``."""
        if self._preemption_handled:
            return "duplicate"
        self._preemption_handled = True
        started = time.monotonic()
        with self._transition_lock:
            inflight = (
                dict(self._inflight_transition)
                if self._inflight_transition is not None else None
            )
        log.warning(
            "PREEMPTION notice: fast-draining within %.0fs (%s)",
            self.preemption_deadline_s,
            f"transition to {inflight['mode']} in flight "
            f"(phase={inflight['phase']})"
            if inflight else "no transition in flight",
        )
        self._emit_node_event(
            "Warning", "CCNodePreempted",
            f"platform preemption notice; fast-draining within "
            f"{self.preemption_deadline_s:.0f}s",
        )
        with trace_mod.root_span(
            "preemption", journal=self.journal, node=self.node_name,
            deadline_s=self.preemption_deadline_s,
        ):
            if self.evict_components:
                try:
                    evict.fast_drain_components(
                        self.api,
                        self.node_name,
                        self.operator_namespace,
                        deadline_s=self.preemption_deadline_s,
                        poll_interval_s=min(
                            self.eviction_poll_interval_s,
                            evict.FAST_DRAIN_POLL_INTERVAL_S,
                        ),
                    )
                except Exception as e:  # noqa: BLE001 - the handoff
                    # publish below matters more than a clean drain; any
                    # failure shape here must not consume its window.
                    log.warning(
                        "fast drain failed (%s); proceeding to the "
                        "handoff publish", e,
                    )
            # Re-read AFTER the drain: the fast drain can run for most of
            # the deadline, and a transition the watch loop started during
            # it must still be handed off — while one that COMPLETED
            # during the drain must NOT be (the pre-drain snapshot above
            # is only for the log line; publishing it would make the
            # replacement spuriously count a 'resumed' flip). Copy
            # defensively — the reconcile thread keeps advancing the
            # phase field while the publish serializes it.
            with self._transition_lock:
                live = self._inflight_transition
                inflight = dict(live) if live is not None else None
            outcome = "clean"
            if inflight is not None:
                outcome = self._publish_handoff(inflight)
                if inflight.get("multi_host"):
                    slicecoord.fence_departed_peer(
                        self.api, self.node_name,
                        str(inflight.get("slice_id") or ""),
                        reason="preempted", metrics=self.metrics,
                    )
        self.metrics.set_fast_drain_seconds(time.monotonic() - started)
        self.metrics.record_preemption(outcome)
        log.warning(
            "preemption handling finished in %.2fs (outcome=%s); awaiting "
            "the platform kill", time.monotonic() - started, outcome,
        )
        return outcome

    def _publish_handoff(self, inflight: dict) -> str:
        """Journal + publish the interrupted transition for the
        replacement node. The journal record is local crash truth (a
        cancelled reclaim replays it as a no-op commit); the annotation
        is what actually survives — the reclaim takes the disk."""
        record = {
            "mode": inflight.get("mode"),
            "phase": inflight.get("phase"),
            "chips": inflight.get("chips"),
            "slice_id": inflight.get("slice_id"),
            "from": self.node_name,
            "ts": round(time.time(), 3),
        }
        txn = self._journal_begin(intent_mod.KIND_HANDOFF, **record)
        try:
            self.api.patch_node_annotations(
                self.node_name,
                {HANDOFF_ANNOTATION: json.dumps(record, sort_keys=True)},
            )
        except Exception as e:  # noqa: BLE001 - count + log; the kill is
            # coming regardless and the caller still fences the slice.
            self._journal_close(txn, ok=False, reason="publish-failed")
            log.error("could not publish the handoff record: %s", e)
            return "handoff-failed"
        self._journal_close(txn, ok=True, published=True)
        log.warning(
            "handoff published: transition to %s (phase=%s) awaits the "
            "replacement node", record["mode"], record["phase"],
        )
        return "handoff"

    def consume_handoff(self) -> None:
        """Startup (replacement-node) half of the handoff: read the
        annotation a preempted predecessor left on this node, remember it
        until the flip completes, and seed the journal's desired-mode
        local truth so even a dark boot knows what it was converging on.
        Best-effort — without the record the normal reconcile still
        converges from the desired label; the handoff only adds intent
        continuity (and the resumed/cleared bookkeeping)."""
        try:
            node = self.api.get_node(self.node_name)
            self._note_api_ok()
        except KubeApiError as e:
            self._note_api_err(e)
            log.debug("handoff check skipped (apiserver unreachable): %s", e)
            return
        from tpu_cc_manager.kubeclient.api import node_annotations

        # Same GET serves the prestage caches: a restarted agent must
        # know it is holding a pre-staged mode BEFORE its initial apply,
        # or that apply would bounce the spare back to the desired mode.
        self._note_prestage(node)
        raw = node_annotations(node).get(HANDOFF_ANNOTATION)
        if not raw:
            return
        try:
            record = json.loads(raw)
            mode = (
                canonical_mode(str(record.get("mode") or ""))
                if isinstance(record, dict)
                else ""
            )
        except ValueError:
            record, mode = None, ""
        if not isinstance(record, dict) or mode not in VALID_MODES:
            log.warning("garbled handoff annotation %r; clearing it", raw[:128])
            self._clear_handoff_annotation()
            return
        self._handoff = record
        log.warning(
            "handoff record found: predecessor %s was preempted mid-flip "
            "to %s (phase=%s); this node resumes the transition",
            record.get("from"), mode, record.get("phase"),
        )
        if self.intents is not None:
            try:
                self.intents.note_desired(mode)
            except intent_mod.JournalError as e:
                log.warning("could not journal the handed-off mode: %s", e)

    def _retire_handoff(self) -> None:
        """After a successful reconcile with a consumed handoff pending:
        the flip the predecessor started is now committed — clear the
        annotation and count the resumption. A failed clear retries on
        the next successful reconcile (the record is stale but harmless:
        consume_handoff runs only at startup)."""
        if self._handoff is None:
            return
        if not self._clear_handoff_annotation():
            return
        self.metrics.record_preemption("resumed")
        self._emit_node_event(
            "Normal", "CCHandoffResumed",
            f"completed the flip to {self._handoff.get('mode')} handed "
            f"off by preempted node agent {self._handoff.get('from')}",
        )
        self._handoff = None

    def _clear_handoff_annotation(self) -> bool:
        try:
            self.api.patch_node_annotations(
                self.node_name, {HANDOFF_ANNOTATION: None}
            )
            return True
        except KubeApiError as e:
            log.warning("could not clear the handoff annotation: %s", e)
            return False

    # ------------------------------------------------------------------
    # Spare pre-staging (zero-bounce flips)
    # ------------------------------------------------------------------

    def _note_prestage(self, node: dict) -> None:
        """Cache the prestage request/status annotations off a node
        object (watch event or startup GET). Garbled values parse to
        None — a pre-staging hint must never fail a reconcile."""
        from tpu_cc_manager.kubeclient.api import node_annotations

        ann = node_annotations(node)
        raw = ann.get(PRESTAGE_ANNOTATION)
        mode = canonical_mode(str(raw)) if raw else ""
        self._prestage_request = mode if mode in VALID_MODES else None
        raw = ann.get(PRESTAGED_ANNOTATION)
        prestaged = None
        if raw:
            try:
                obj = json.loads(raw)
            except ValueError:
                obj = None
            if (
                isinstance(obj, dict)
                and canonical_mode(str(obj.get("mode") or "")) in VALID_MODES
            ):
                prestaged = obj
        if prestaged is None:
            # A snapshot without the status record may simply predate
            # our own publish (events queue behind the prestage pass):
            # the in-process done record outranks a stale view.
            prestaged = self._prestage_done
        self._prestaged = prestaged

    def _maybe_prestage(self, node: dict) -> bool | None:
        """Run a pre-staging pass when the node's annotations ask for
        one: the PRESTAGE annotation names a mode != desired, and the
        node does not already hold it. Returns the pass's outcome for
        the watch loop's backoff bookkeeping (the abort path — request
        deleted mid-hold — returns the revert reconcile's outcome), or
        None when nothing ran."""
        self._note_prestage(node)
        if not self.prestage:
            return None
        labels = node_labels(node)
        desired = self.with_default(labels.get(CC_MODE_LABEL))
        state_label = labels.get(labels_mod.CC_MODE_STATE_LABEL)
        req = self._prestage_request
        done_mode = (
            canonical_mode(str(self._prestaged.get("mode") or ""))
            if self._prestaged is not None else None
        )
        if req is None:
            if done_mode is not None and done_mode == state_label != desired:
                # Possible abort: the request annotation is gone while
                # the node still HOLDS the pre-staged mode. Confirm
                # against a FRESH read first — watch events queued
                # behind a long reconcile can show this shape
                # transiently (e.g. mid-consume after the wave landed).
                fresh = self.api.get_node(self.node_name)
                self._note_prestage(fresh)
                fresh_labels = node_labels(fresh)
                desired = self.with_default(fresh_labels.get(CC_MODE_LABEL))
                state_label = fresh_labels.get(labels_mod.CC_MODE_STATE_LABEL)
                done_mode = (
                    canonical_mode(str(self._prestaged.get("mode") or ""))
                    if self._prestaged is not None else None
                )
                if not (
                    self._prestage_request is None
                    and done_mode is not None
                    and done_mode == state_label != desired
                ):
                    return None
                # Confirmed: clear the status record and reconcile back
                # to the desired mode.
                log.warning(
                    "prestage of mode %s aborted (request annotation "
                    "deleted); reverting to desired mode %s",
                    done_mode, desired,
                )
                self._prestage_done = None
                self._clear_prestaged_annotation()
                return self.set_cc_mode(desired)
            return None
        if req == desired:
            # Moot: the wave arrived before (or instead of) the
            # prestage pass — the normal desired-mode reconcile owns
            # convergence and its success consumes the request.
            return None
        if done_mode == req and state_label == req:
            return None  # already pre-staged and holding
        if self._prestage_done is not None and canonical_mode(
            str(self._prestage_done.get("mode") or "")
        ) == req:
            # This process already completed the pass; the snapshot is a
            # stale mid-transition view queued behind it.
            return None
        return self._run_prestage(req, desired)

    def _run_prestage(self, mode: str, prior: str) -> bool:
        """The pre-staging pass itself: the FULL journaled transition
        (drain/stage/reset/verify/warmup-backed smoke/readmit — crash
        replay included) run against the annotation's mode while the
        desired label still says ``prior``. The state label ends
        truthful (it reports what the hardware holds); the hold guard
        in _set_cc_mode keeps later reconciles from bouncing the spare
        back until the wave's desired write lands or the request is
        deleted."""
        log.warning(
            "pre-staging CC mode %s ahead of its rollout wave "
            "(desired stays %s until the wave opens)", mode, prior,
        )
        t0 = time.monotonic()
        self._in_prestage = True
        self.metrics.set_prestage_in_progress(True)
        try:
            ok = self.set_cc_mode(mode)
        finally:
            self._in_prestage = False
            self.metrics.set_prestage_in_progress(False)
        seconds = round(time.monotonic() - t0, 3)
        self.metrics.set_spare_prestage_seconds(seconds)
        if not ok:
            # The spare stays on the normal failed-reconcile path (the
            # backoff retry re-applies the DESIRED mode, reverting any
            # partial prestage); the orchestrator's prestage await times
            # out and the wave falls back to a full flip.
            log.error(
                "pre-staging of mode %s FAILED after %.1fs; the wave "
                "falls back to a full flip", mode, seconds,
            )
            self._emit_node_event(
                "Warning", "CCPrestageFailed",
                f"pre-staging of CC mode {mode} failed",
            )
            return False
        record = {
            "mode": mode,
            "prior": prior,
            "seconds": seconds,
            "ts": round(time.time(), 3),
        }
        self._prestage_done = record
        try:
            self.api.patch_node_annotations(
                self.node_name,
                {PRESTAGED_ANNOTATION: json.dumps(record, sort_keys=True)},
            )
        except KubeApiError as e:
            # The orchestrator never sees the record and falls back to a
            # full-flip await; the hold still engages off the local
            # cache, and the next successful publish heals it.
            log.warning("could not publish the prestaged record: %s", e)
        self._prestaged = record
        self._emit_node_event(
            "Normal", "CCNodePrestaged",
            f"pre-staged CC mode {mode} in {seconds}s; holding for the "
            "rollout wave",
        )
        return True

    def _prestage_hold(self, mode: str, chips: tuple[TpuChip, ...]) -> bool:
        """True while this node deliberately HOLDS a pre-staged mode
        that differs from the desired one — the PRESTAGE annotation is
        the suppression: without it, the first desired!=state reconcile
        would bounce the spare straight back and waste the pre-staged
        flip. The hold only binds against the desired mode recorded at
        prestage time: a desired change to any THIRD mode breaks it and
        reconciles normally (the pool moved on; the prestage is stale)."""
        if self._in_prestage or not self.prestage:
            return False
        req, done = self._prestage_request, self._prestaged
        if req is None or done is None or req == mode:
            return False
        if canonical_mode(str(done.get("mode") or "")) != req:
            return False
        if canonical_mode(str(done.get("prior") or "")) != mode:
            return False
        if not self._mode_is_set(chips, req):
            return False
        log.info(
            "holding pre-staged mode %s (desired %s unchanged since the "
            "prestage); the rollout wave's desired write completes the "
            "flip instantly", req, mode,
        )
        return True

    def _consume_prestage(self, mode: str) -> None:
        """Housekeeping after a successful DESIRED-mode reconcile: a
        matching prestage request is consumed (the wave arrived — the
        PRESTAGED status record stays behind as the operator-visible
        explanation of why the wave opened instantly); a record for a
        DIFFERENT mode is stale (the pool moved on past it) and both
        annotations clear so the hold cannot re-engage."""
        if self._in_prestage:
            return
        cleared_req = False
        if self._prestage_request is not None and self._prestage_request == mode:
            cleared_req = self._clear_prestage_request()
        done = self._prestaged
        if done is not None and canonical_mode(
            str(done.get("mode") or "")
        ) != mode:
            # The pool moved past the pre-staged mode: the record (and
            # this process's done copy) is stale.
            self._prestage_done = None
            if not cleared_req and self._prestage_request is not None:
                self._clear_prestage_request()
            self._clear_prestaged_annotation()

    def _clear_prestage_request(self) -> bool:
        try:
            self.api.patch_node_annotations(
                self.node_name, {PRESTAGE_ANNOTATION: None}
            )
        except KubeApiError as e:
            # Cache keeps the value; the next successful reconcile
            # retries the clear.
            log.warning("could not clear the prestage request: %s", e)
            return False
        self._prestage_request = None
        return True

    def _clear_prestaged_annotation(self) -> None:
        try:
            self.api.patch_node_annotations(
                self.node_name, {PRESTAGED_ANNOTATION: None}
            )
        except KubeApiError as e:
            log.warning("could not clear the prestaged record: %s", e)
            return
        self._prestaged = None

    def _start_preemption_monitor(self) -> None:
        """Poll the backend's preemption-notice source (GCE: metadata
        ``instance/preempted``) on a daemon thread; the first notice runs
        handle_preemption_notice and the thread retires (the signal is
        level-triggered — one reclaim per VM lifetime)."""
        if self.preemption_poll_s <= 0 or self.preemption_deadline_s <= 0:
            return
        if self._preemption_thread is not None:
            return
        stop = threading.Event()

        def loop() -> None:
            while not stop.wait(self.preemption_poll_s):
                try:
                    if self.backend.preemption_notice():
                        self.handle_preemption_notice()
                        return
                except Exception as e:  # noqa: BLE001 - a flaky notice
                    # source must never kill the monitor (or the agent).
                    log.debug("preemption poll failed (non-fatal): %s", e)

        self._preemption_stop = stop
        self._preemption_thread = threading.Thread(
            target=loop, name="preemption-monitor", daemon=True
        )
        self._preemption_thread.start()

    def _stop_preemption_monitor(self) -> None:
        if self._preemption_stop is not None:
            self._preemption_stop.set()
        if self._preemption_thread is not None:
            self._preemption_thread.join(timeout=2.0)
        self._preemption_stop = None
        self._preemption_thread = None

    # ------------------------------------------------------------------
    # Watch loop (reference call stack 3.4)
    # ------------------------------------------------------------------

    def _startup_mode_read(
        self, stop: threading.Event | None = None
    ) -> tuple[str | None, str] | None:
        """The boot-time desired-mode read, ordered journal → hardware →
        apiserver (recover_from_journal has already run).

        Two divergences from the reference's fatal first GET:

        - **Outage autonomy**: when the apiserver is unreachable AND the
          journal holds a last-known desired mode, the agent keeps serving
          that mode and retries the read on the jittered ladder instead of
          crash-looping — the hardware is already converged (or journal
          replay converged it) and a restart loop would add nothing. With
          no local truth (fresh node, no journal) the GET stays fatal by
          design: crash-as-retry.
        - **Stale-read guard**: a first read that DISAGREES with the
          journaled last-acted-on mode is confirmed with a second read
          before anything acts on it. During a flaky boot (a blackout
          ending mid-boot, a lagging replica) a single stale label must
          not trigger a spurious hardware transition; the confirming read
          either re-errors with an outage (still flaky — keep serving
          local truth, wait out the ladder, retry), fails fatally on a
          real API error (the server answered: same semantics as the
          first read), or returns the fresher value, which wins.

        Returns (label, rv), or None when ``stop`` was set while waiting
        out an outage."""
        attempts = 0

        def wait_out() -> bool:
            """One jittered-ladder wait between boot-time read attempts;
            False when ``stop`` was set while waiting."""
            nonlocal attempts
            attempts += 1
            delay = self._reconnect_policy.delay_for(min(attempts - 1, 8))
            return not retry_mod.wait(delay, stop)

        while True:
            try:
                label, rv = self.get_node_cc_mode_label()
                self._note_api_ok()
            except KubeApiError as e:
                self._note_api_err(e)
                local = (
                    self.intents.last_desired_mode
                    if self.intents is not None else None
                )
                if local is None or not intent_mod.is_outage_error(e):
                    raise  # no local truth (or a real API error): fatal
                log.warning(
                    "apiserver unreachable at boot (%s); serving last-known "
                    "desired mode %r from the intent journal "
                    "(offline %.0fs)", e, local, self.offline.offline_seconds,
                )
                if not wait_out():
                    return None
                continue
            local = (
                self.intents.last_desired_mode
                if self.intents is not None else None
            )
            if local is not None and self.with_default(label) != local:
                try:
                    label2, rv2 = self.get_node_cc_mode_label()
                    self._note_api_ok()
                except KubeApiError as e:
                    self._note_api_err(e)
                    if not intent_mod.is_outage_error(e):
                        raise  # the server ANSWERED: fatal, like read 1
                    log.warning(
                        "boot-time desired mode %r disagrees with the "
                        "journaled %r and could not be confirmed (%s); "
                        "keeping local truth and retrying", label, local, e,
                    )
                    if not wait_out():
                        return None
                    continue
                if (label2, rv2) != (label, rv):
                    log.info(
                        "boot-time confirm read superseded %r with %r",
                        label, label2,
                    )
                label, rv = label2, rv2
            return label, rv

    def watch_and_apply(self, stop: threading.Event | None = None) -> None:
        try:
            self._watch_and_apply(stop)
        finally:
            # The slice-peer informer's watch thread must not outlive the
            # agent loop (tests and clean shutdowns alike).
            self._stop_peer_informer()
            self._stop_preemption_monitor()

    def _watch_and_apply(self, stop: threading.Event | None = None) -> None:
        """Initial apply, then watch the node label forever.

        Semantics preserved from the reference (main.py:600-684): rv
        tracking, 300 s server-side watch timeout, ERROR-event handling,
        410-Gone resync via re-GET + conditional re-apply, consecutive-error
        cap of 10 (reset on any successful event — documented quirk,
        SURVEY.md §8.6), 5 s reconnect delay (with ``time`` imported; the
        reference's missing import made this path fatal, SURVEY.md §8.1).
        ``stop`` makes the loop exitable for tests and graceful shutdown.

        Divergence from the reference (deliberate): a FAILED reconcile is
        retried with exponential backoff (retry_backoff_s, doubling to
        retry_backoff_max_s) without requiring the label to change — the
        reference leaves the node 'failed' until the next label edit. That
        includes a reconcile ABORTED by an apiserver error escaping the
        apply (the failed-state patch itself failing): it is noted failed
        and retried, not lost until the next label edit.
        """
        last_label_value: str | None = None
        consecutive_errors = 0
        # Failed-reconcile retry state (VERDICT r2 item 6): a failed apply
        # schedules a re-apply with exponential backoff instead of waiting
        # for the next label change.
        retry_at: float | None = None
        backoff = self.retry_backoff_s

        def note_result(ok: bool) -> bool:
            nonlocal retry_at, backoff
            # Feed the remediation ladder (ccmanager/remediation.py): a
            # success resets it; a RETRYABLE failure escalates it (stable
            # misconfigurations — invalid mode, unsupported hardware —
            # can't be remediated by resets, and a quarantined node's
            # deferred reconciles must not re-escalate).
            if self.remediation is not None:
                if ok:
                    self.remediation.note_success()
                elif self.retryable_failure and not self.remediation.quarantined:
                    self.remediation.note_failure(
                        self.last_failure_reason or "apply-failed"
                    )
            if ok or self.retry_backoff_s <= 0:
                retry_at = None
                backoff = self.retry_backoff_s
            else:
                # Stable misconfigurations skip the fast doubling ladder and
                # go straight to the slow cadence: an identical re-fail
                # every few seconds helps nobody, but a later hardware/pool
                # fix should still converge without a label edit.
                delay = (
                    backoff if self.retryable_failure
                    else self.retry_backoff_max_s
                )
                retry_at = time.monotonic() + delay
                log.warning(
                    "reconcile failed; retrying in %.0fs without waiting for "
                    "a label change", delay,
                )
                backoff = min(backoff * 2, self.retry_backoff_max_s)
            return ok

        def apply_noted(value: str | None) -> bool:
            """In-watch reconcile: an apiserver error ESCAPING the apply
            (e.g. the failed-state patch itself exhausted its retries) is
            noted as a failed reconcile so the backoff retry still fires —
            before this, the exception unwound to the reconnect handler and
            the reconcile was silently lost until the next label edit.
            Device-layer crash-as-retry (sys.exit on mixed capability) and
            the fatal startup GET are unaffected."""
            try:
                return note_result(self.set_cc_mode(self.with_default(value)))
            except KubeApiError as e:
                self._note_api_err(e)
                log.warning(
                    "reconcile aborted by apiserver error (%s); scheduling "
                    "backoff retry", e,
                )
                # No record_failure here: most escape paths already counted
                # their reason before the state patch raised, and a second
                # count would make sum(tpu_cc_failures_total) exceed the
                # failed-reconcile total during every apiserver incident.
                return note_result(False)

        def maybe_retry() -> None:
            if retry_at is not None and time.monotonic() >= retry_at:
                log.info("retrying failed reconcile")
                apply_noted(last_label_value)

        def prestage_noted(node: dict) -> None:
            """Prestage pass with the same escaped-apiserver-error
            discipline as apply_noted: an aborted pass schedules the
            backoff retry (which re-applies the DESIRED mode, reverting
            any partial prestage — the safe direction)."""
            try:
                pre = self._maybe_prestage(node)
            except KubeApiError as e:
                self._note_api_err(e)
                log.warning(
                    "pre-staging aborted by apiserver error (%s); "
                    "scheduling backoff retry", e,
                )
                pre = False
            if pre is not None:
                note_result(pre)

        # The preemption monitor starts FIRST: a spot VM can be reclaimed
        # while the agent is still booting, and the fast-drain + handoff
        # window is too short to wait for the watch loop to settle.
        self._start_preemption_monitor()
        # Boot ordering: journal replay and hardware-truth recovery run
        # BEFORE the first apiserver read, and that read is stale-guarded
        # and outage-tolerant (_startup_mode_read).
        self.recover_from_journal()
        first = self._startup_mode_read(stop)
        if first is None:
            return  # stopped while riding out an apiserver outage
        label, rv = first
        # A handoff record a preempted predecessor left on this node: the
        # first reconcile below completes (or supersedes) the handed-off
        # flip and retires the record.
        self.consume_handoff()
        note_result(self.set_cc_mode(self.with_default(label)))
        self.create_readiness_file()
        last_label_value = label
        try:
            # A challenge issued while the agent was down must not wait
            # for the next label edit to be answered.
            node0 = self.api.get_node(self.node_name)
            self._maybe_answer_challenge(node0)
        except KubeApiError as e:
            log.debug("startup challenge check failed (non-fatal): %s", e)
        else:
            # Likewise a prestage request that landed while the agent
            # was down (or survived its restart) runs now, not at the
            # next annotation edit.
            prestage_noted(node0)

        while not (stop and stop.is_set()):
            timeout = self.watch_timeout_s
            if retry_at is not None:
                # Bound the watch so the retry fires even on a quiet node.
                timeout = max(
                    1, min(timeout, int(retry_at - time.monotonic()) + 1)
                )
            try:
                for event in self.api.watch_nodes(
                    self.node_name, rv or None, timeout
                ):
                    if stop and stop.is_set():
                        return
                    if event.type == "ERROR":
                        code = (event.object or {}).get("code")
                        if code == 410:
                            raise KubeApiError(410, "watch ERROR event: Gone")
                        consecutive_errors += 1
                        log.warning(
                            "watch ERROR event (%s/%s): %s",
                            consecutive_errors, self.max_watch_errors, event.object,
                        )
                        if consecutive_errors >= self.max_watch_errors:
                            # Divergence from the reference, which only caps
                            # ApiExceptions (main.py:659-668): a stream of
                            # ERROR events is equally hopeless.
                            raise RuntimeError(
                                f"{consecutive_errors} consecutive watch ERROR "
                                f"events; giving up (pod restart acts as recovery)"
                            )
                        break
                    consecutive_errors = 0
                    self._note_api_ok()
                    rv = resource_version(event.object) or rv
                    if event.type == "BOOKMARK":
                        # Bookmarks carry ONLY metadata.resourceVersion — no
                        # labels. Falling through would misread the desired
                        # mode as absent and fire a spurious reconcile to
                        # the default. Track the rv (that is their whole
                        # point: a fresh rv on quiet nodes keeps reconnects
                        # from 410-expiring) and move on.
                        maybe_retry()
                        continue
                    event_labels = node_labels(event.object)
                    value = event_labels.get(CC_MODE_LABEL)
                    # The stitching hint rides in the SAME patch as the
                    # desired mode, so this event carries both.
                    self._note_rollout_trace(event_labels)
                    self._maybe_answer_challenge(event.object)
                    # Refresh the prestage caches on EVERY event: the
                    # apply below consults them (hold guard + consume)
                    # even when this event is a desired-label change.
                    self._note_prestage(event.object)
                    if value != last_label_value:
                        log.info(
                            "%s changed: %r -> %r",
                            CC_MODE_LABEL, last_label_value, value,
                        )
                        last_label_value = value
                        if not apply_noted(value):
                            # The already-open stream keeps its original
                            # (up to 300 s) server-side timeout; on a quiet
                            # node that would delay the backoff retry far
                            # past retry_at. Reconnect with the bounded
                            # timeout instead (rv is tracked, nothing is
                            # lost).
                            break
                    else:
                        # Prestage requests ride node-annotation events;
                        # only considered while the desired label is
                        # quiet — a pending desired change always wins.
                        prestage_noted(event.object)
                        maybe_retry()
                else:
                    # Stream ended normally (server-side timeout): the
                    # apiserver answered, so the outage clock resets and
                    # any deferred patches flush even on a QUIET node
                    # whose stream carries no events. Then retry a failed
                    # reconcile if due — unless shutdown is in progress (a
                    # retry started after SIGTERM would race the hard-exit
                    # fallback) — and reconnect with the tracked rv.
                    self._note_api_ok()
                    if not (stop and stop.is_set()):
                        maybe_retry()
                    continue
            except KubeApiError as e:
                self._note_api_err(e)
                consecutive_errors += 1
                # Disconnected-mode ladder: once a TOTAL outage outlasts
                # CC_OFFLINE_GRACE_S (and the journal holds local truth),
                # the agent stops treating the error cap as fatal — a
                # crash-exit would gain nothing, and the node keeps
                # serving its last-known desired mode while label writes
                # defer into the journal. Reconnects continue on the
                # capped jittered ladder.
                offline_autonomy = (
                    self.intents is not None
                    and self.offline.engaged
                    and intent_mod.is_outage_error(e)
                )
                if consecutive_errors >= self.max_watch_errors:
                    if not offline_autonomy:
                        raise RuntimeError(
                            f"{consecutive_errors} consecutive watch errors; "
                            f"giving up (pod restart acts as recovery)"
                        ) from e
                    log.warning(
                        "disconnected mode: apiserver dark for %.0fs "
                        "(%d consecutive watch errors); serving last-known "
                        "desired mode %r from the intent journal",
                        self.offline.offline_seconds, consecutive_errors,
                        self.intents.last_desired_mode,
                    )
                delay = self._reconnect_policy.delay_for(
                    min(max(0, consecutive_errors - 1), 16)
                )
                if e.status == 410:
                    log.info("watch resourceVersion expired; resyncing")
                    try:
                        value, rv = self.get_node_cc_mode_label()
                    except KubeApiError as e2:
                        log.warning("resync GET failed: %s", e2)
                        self.metrics.record_retry("watch.resync", "apiserver")
                        if retry_mod.wait(delay, stop):
                            return
                        continue
                    if value != last_label_value:
                        last_label_value = value
                        apply_noted(value)
                    continue
                log.warning(
                    "watch error (%s/%s): %s — reconnecting in %.1fs",
                    consecutive_errors, self.max_watch_errors, e, delay,
                )
                self.metrics.record_retry("watch.reconnect", "watch-error")
                if retry_mod.wait(delay, stop):
                    return

    def remove_readiness_file(self) -> None:
        """Best-effort in-process counterpart of the preStop ``/bin/rm``
        hook (reference Dockerfile.distroless:45-46): a gracefully stopping
        agent withdraws its readiness signal itself, so the operator's
        validation framework notices even when the preStop hook is skipped
        (e.g. node shutdown)."""
        try:
            os.remove(self.readiness_file)
            log.info("removed readiness file %s", self.readiness_file)
        except FileNotFoundError:
            pass
        except OSError as e:
            log.warning("could not remove readiness file: %s", e)

    def run(self, stop: threading.Event | None = None) -> None:
        """Entry point (reference main.py:693-695). On a graceful stop the
        readiness file is withdrawn before returning."""
        log.info(
            "starting tpu-cc-manager on node %s (default=%s evict=%s smoke=%s ns=%s)",
            self.node_name, self.default_mode, self.evict_components,
            self.smoke_workload, self.operator_namespace,
        )
        try:
            self.watch_and_apply(stop)
        finally:
            if stop is not None and stop.is_set():
                self.remove_readiness_file()
