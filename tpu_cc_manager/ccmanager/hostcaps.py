"""Host confidential-computing capability detection.

Reference: is_host_cc_enabled() (main.py:80-103) probes
/sys/module/kvm_intel/parameters/tdx and /sys/module/kvm_amd/parameters/sev_snp
— i.e. "can this host run CC guests". A TPU VM is itself the guest, so the
equivalent question is "is this VM confidential": probed via the TDX/SEV
guest device nodes, with the reference's KVM-host probes kept for the case
where the agent runs on a bare-metal host managing CC guest VMs.
"""

from __future__ import annotations

import logging
import os

log = logging.getLogger(__name__)

# (description, path, expected-content prefix or None for existence-only)
_DEFAULT_PROBES: tuple[tuple[str, str, str | None], ...] = (
    ("TDX guest device", "/dev/tdx_guest", None),
    ("SEV guest device", "/dev/sev-guest", None),
    ("KVM Intel TDX host support", "/sys/module/kvm_intel/parameters/tdx", "Y"),
    ("KVM AMD SEV-SNP host support", "/sys/module/kvm_amd/parameters/sev_snp", "Y"),
)


def is_host_cc_enabled(
    probes: tuple[tuple[str, str, str | None], ...] = _DEFAULT_PROBES,
) -> bool:
    """True if any probe indicates confidential-computing capability."""
    for desc, path, expect in probes:
        if not os.path.exists(path):
            continue
        if expect is None:
            log.info("host CC capability: %s present (%s)", desc, path)
            return True
        try:
            with open(path, "r", encoding="utf-8") as f:
                content = f.read().strip()
        except OSError as e:
            log.debug("probe %s unreadable: %s", path, e)
            continue
        if content.upper().startswith(expect.upper()):
            log.info("host CC capability: %s enabled (%s=%s)", desc, path, content)
            return True
    log.warning("no host CC capability detected (probed %d locations)", len(probes))
    return False
