"""Node-local write-ahead intent log + disconnected-mode state.

Every durable record the agent relied on before this module — mode/ready
state labels, the remediation annotation, barrier markers, the rollout
record — lives in the apiserver. A node that loses the control plane mid
hardware transition (or is SIGKILLed while disconnected) restarted with no
authoritative record of what it was doing to the chips. The reference's
core discipline is "read truth back from the hardware" (main.py:524-528);
extending that to *crash* truth requires a node-local, crash-consistent
journal — the same move kubelet makes with its checkpoint store.

The journal lives in the backend's state dir (the writable host mount that
already stages ``CC_RUNTIME_ENV_FILE``), one record per line::

    TCCJ1 <crc32-hex8> {"seq": N, "t": "intent", ...}\n

- **CRC-framed**: the crc32 covers the JSON payload bytes; a record whose
  frame doesn't verify ends the readable prefix.
- **fsync'd, append-only**: every append is written and fsync'd before the
  hardware-effecting operation it describes runs, so the journal can claim
  *intent happened-before effect*.
- **Torn-tail truncation on replay**: a crash mid-append leaves a partial
  (or CRC-failing) final record; replay truncates the file back to the
  last verifiable record and carries on. Corruption strictly *mid*-file
  (verifiable records FOLLOW the bad bytes — bit rot, not a torn write)
  is not silently skipped: replay fails closed (:class:`JournalCorrupt`),
  the caller feeds the remediation ladder, and the corrupt file is moved
  aside so the node re-derives state from hardware truth alone.

Record grammar (the ``t`` field):

==================  ======================================================
``intent``          a hardware-effecting operation is about to start
                    (``kind=transition``: the stage/reset/verify pipeline;
                    ``kind=drain``: the pause/readmit bracket — the paused
                    set itself lives in the node's pause-encoded labels,
                    so recovery restores it with one readmit once the
                    apiserver answers; ``kind=handoff``: a preemption
                    notice interrupted an in-flight transition — the same
                    record is also published to the node's handoff
                    annotation, because a preempted VM's DISK dies with
                    it and the replacement node can only read the
                    apiserver copy)
``mark``            phase progress inside an open intent (``staged`` →
                    ``reset``), so replay knows whether the disruptive
                    reset had begun
``commit``/``abort``  the intent finished / was rolled back
``desired``         the last desired mode this agent acted on — boot-time
                    local truth when the apiserver is unreachable
``patch``           a node-label write deferred while disconnected
                    (flushed idempotently on reconnect — RMW, not blind
                    replay)
``flushed``         every ``patch`` at or below this seq has been flushed
==================  ======================================================

:class:`OfflineTracker` is the disconnected-mode ladder's clock: after
``CC_OFFLINE_GRACE_S`` of *total* apiserver outage (transport-level
failures only — a 403 is not an outage) the agent keeps serving its
last-known desired mode and defers label writes into the journal.
"""

from __future__ import annotations

import json
import logging
import os
import time
import zlib

from tpu_cc_manager.utils import locks as locks_mod

log = logging.getLogger(__name__)

MAGIC = "TCCJ1"
JOURNAL_FILE = "intent.journal"
# Compact (rewrite with only live state) when the file outgrows this.
DEFAULT_MAX_BYTES = 1 << 20

OFFLINE_GRACE_ENV = "CC_OFFLINE_GRACE_S"
DEFAULT_OFFLINE_GRACE_S = 60.0

# Transition phases, in pipeline order. Replay's decision table:
#   phase < reset  -> nothing disruptive ran; roll BACK (abort, clear staged)
#   phase >= reset -> the reset may have committed; ask the hardware —
#                     complete if every chip reports the intended mode,
#                     otherwise the reset provably didn't land (tpuvm's
#                     pending markers report 'resetting') and the normal
#                     reconcile re-applies: never a duplicate device reset.
PHASE_BEGUN = "begun"
PHASE_STAGED = "staged"
PHASE_RESET = "reset"
#: Mark on a DRAIN intent: the pipelined readmit was kicked off while
#: the smoke workload runs (readmit ∥ smoke). Purely diagnostic — drain
#: recovery keys on the intent being open, not its phase — but a
#: crash-dump reader can tell "died before any readmit started" from
#: "died with the readmit in flight".
PHASE_READMIT = "readmit"

# Intent kinds (the ``kind`` field of t=intent records).
KIND_TRANSITION = "transition"
KIND_DRAIN = "drain"
#: A preemption notice interrupted an in-flight transition: the agent
#: journals it locally (crash truth if the preemption is cancelled) AND
#: mirrors it to the node's handoff annotation (ccmanager/manager.py
#: HANDOFF_ANNOTATION) — the replacement VM has a fresh disk, so the
#: apiserver copy is the only record that survives the reclaim.
KIND_HANDOFF = "handoff"
#: A remediation-ladder hardware rung (device re-reset / runtime restart,
#: ccmanager/remediation.py) — journaled like any hardware-effecting
#: operation (the cclint journal-before-reset contract). Replay found one
#: open = the agent died mid-rung: the backend's own pending markers and
#: the persisted ladder annotation already carry the recovery state, so
#: the intent is simply closed and the normal reconcile re-drives.
KIND_REMEDIATION = "remediation"


class JournalCorrupt(Exception):
    """Replay found verifiable records AFTER unverifiable bytes — not a
    torn tail but real corruption. The journal cannot be trusted as a
    prefix; callers fail closed into the remediation ladder."""


class JournalError(Exception):
    """The journal file could not be written (disk fault, read-only
    mount). Hardware-effecting callers must treat this like any other
    failed precondition: no intent record, no transition."""


def _frame(payload: dict) -> bytes:
    data = json.dumps(payload, sort_keys=True, separators=(",", ":"))
    raw = data.encode("utf-8")
    return f"{MAGIC} {zlib.crc32(raw) & 0xFFFFFFFF:08x} ".encode() + raw + b"\n"


def _parse_line(line: bytes) -> dict | None:
    """Decode one framed record; None when the frame doesn't verify."""
    try:
        head, crc_hex, raw = line.split(b" ", 2)
    except ValueError:
        return None
    if head != MAGIC.encode() or len(crc_hex) != 8:
        return None
    try:
        crc = int(crc_hex, 16)
    except ValueError:
        return None
    if zlib.crc32(raw) & 0xFFFFFFFF != crc:
        return None
    try:
        rec = json.loads(raw)
    except (ValueError, UnicodeDecodeError):
        return None
    if not isinstance(rec, dict) or not isinstance(rec.get("seq"), int):
        return None
    return rec


class ReplayResult:
    """What a replay recovered: the verifiable record prefix and how many
    bytes of torn tail were truncated."""

    def __init__(self, records: list[dict], truncated_bytes: int):
        self.records = records
        self.truncated_bytes = truncated_bytes


class IntentJournal:
    """Crash-consistent intent log. Thread-safe (the watch loop journals
    transitions while the watchdog defers patches)."""

    def __init__(
        self,
        path: str,
        max_bytes: int = DEFAULT_MAX_BYTES,
        fsync: bool = True,
    ) -> None:
        self.path = path
        self.max_bytes = max_bytes
        self._fsync = fsync
        self._lock = locks_mod.make_rlock("intent-journal")
        self._fd: int | None = None  # cclint: guarded-by(_lock)
        self._seq = 0  # cclint: guarded-by(_lock)
        self._txn_counter = 0  # cclint: guarded-by(_lock)
        # Live state, maintained on every append so readers (the /journalz
        # endpoint, recovery) never re-parse the file.
        self._open_intents: dict[str, dict] = {}  # cclint: guarded-by(_lock)
        self._pending_patches: list[dict] = []  # t=patch records  # cclint: guarded-by(_lock)
        self._flushed_upto = 0  # cclint: guarded-by(_lock)
        self._last_desired: str | None = None  # cclint: guarded-by(_lock)
        self._tail: list[dict] = []  # bounded recent-record window  # cclint: guarded-by(_lock)
        self.last_replay: dict | None = None
        # Chaos hook (faults/plan.py disk-fault mode): the next N appends
        # raise JournalError as if the state-dir disk faulted mid-write.
        self.fail_appends = 0

    @classmethod
    def from_state_dir(cls, state_dir: str, **kwargs) -> "IntentJournal":
        return cls(os.path.join(state_dir, JOURNAL_FILE), **kwargs)

    # ---- low-level append -------------------------------------------------

    def _ensure_open(self) -> int:  # cclint: requires(_lock)
        if self._fd is None:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            self._fd = os.open(
                self.path, os.O_WRONLY | os.O_APPEND | os.O_CREAT, 0o600
            )
        return self._fd

    def _append(self, record: dict) -> dict:
        with self._lock:
            if self.fail_appends:
                self.fail_appends -= 1
                raise JournalError(
                    f"injected disk fault writing {self.path}"
                )
            self._seq += 1
            record = {"seq": self._seq, "ts": round(time.time(), 3), **record}
            frame = _frame(record)
            try:
                fd = self._ensure_open()
                os.write(fd, frame)
                if self._fsync:
                    os.fsync(fd)
            except OSError as e:
                # A journal that cannot persist must not pretend it did:
                # the in-memory seq rolls back and the caller decides
                # whether the operation may proceed unjournaled.
                self._seq -= 1
                self._close_fd()
                raise JournalError(f"could not append to {self.path}: {e}") from e
            self._apply(record)
            return record

    def _close_fd(self) -> None:  # cclint: requires(_lock)
        if self._fd is not None:
            try:
                os.close(self._fd)
            except OSError:
                pass
            self._fd = None

    def _apply(self, rec: dict) -> None:  # cclint: requires(_lock)
        """Fold one record into the live state (append and replay share
        this, so recovery sees exactly what a running agent would)."""
        t = rec.get("t")
        if t == "intent":
            self._open_intents[rec["txn"]] = dict(rec)
        elif t == "mark":
            intent = self._open_intents.get(rec.get("txn", ""))
            if intent is not None:
                intent["phase"] = rec.get("phase")
        elif t in ("commit", "abort"):
            self._open_intents.pop(rec.get("txn", ""), None)
        elif t == "desired":
            self._last_desired = rec.get("mode")
        elif t == "patch":
            self._pending_patches.append(rec)
        elif t == "flushed":
            upto = rec.get("upto", 0)
            self._flushed_upto = max(self._flushed_upto, upto)
            self._pending_patches = [
                p for p in self._pending_patches if p["seq"] > upto
            ]
        self._tail.append(rec)
        if len(self._tail) > 64:
            del self._tail[: len(self._tail) - 64]

    # ---- replay -----------------------------------------------------------

    def replay(self) -> ReplayResult:
        """Read the journal back, truncate a torn tail, fail closed on
        mid-file corruption, and rebuild the live state. Call once at
        startup, before the first apiserver read."""
        with self._lock:
            self._close_fd()
            try:
                with open(self.path, "rb") as f:
                    data = f.read()
            except FileNotFoundError:
                self.last_replay = {"records": 0, "truncated_bytes": 0}
                return ReplayResult([], 0)
            records: list[dict] = []
            good_end = 0  # byte offset one past the last verifiable record
            offset = 0
            corrupt_at: int | None = None
            last_seq = 0
            # Only COMPLETE (newline-terminated) lines are parseable: a
            # final fragment with no newline is always a torn tail, even
            # when its CRC happens to verify — accepting it would leave
            # the file ending mid-line, and the next append would glue a
            # fresh record onto it, turning a benign torn write into
            # mid-file corruption at the replay after that.
            lines = data.split(b"\n")
            lines.pop()  # bytes after the last newline ('' when none)
            for line in lines:
                line_end = offset + len(line) + 1  # +1 for the newline
                if line:
                    rec = _parse_line(line)
                    if rec is not None and rec["seq"] <= last_seq:
                        # A CRC-VALID record whose seq does not strictly
                        # increase can only be a duplicated or reordered
                        # record — a torn write cannot produce one (the
                        # CRC frame would not verify). Truncating here
                        # would silently discard real later records, so
                        # this always fails closed.
                        self._quarantine_file()
                        raise JournalCorrupt(
                            f"{self.path}: record at byte {offset} has "
                            f"seq {rec['seq']} <= {last_seq} — duplicated "
                            "or reordered records, not a torn tail"
                        )
                    if rec is None:
                        if corrupt_at is None:
                            corrupt_at = offset
                    elif corrupt_at is not None:
                        # Verifiable records after unverifiable bytes:
                        # this is not a torn tail. Move the file aside so
                        # the next boot starts clean, then fail closed.
                        self._quarantine_file()
                        raise JournalCorrupt(
                            f"{self.path}: unverifiable record at byte "
                            f"{corrupt_at} followed by verifiable data at "
                            f"byte {offset} — not a torn tail"
                        )
                    else:
                        records.append(rec)
                        last_seq = rec["seq"]
                        good_end = line_end
                offset = line_end
            truncated = len(data) - good_end
            if truncated:
                log.warning(
                    "intent journal %s: truncating %d byte(s) of torn tail "
                    "after %d verifiable record(s)",
                    self.path, truncated, len(records),
                )
                with open(self.path, "r+b") as f:
                    f.truncate(good_end)
                    if self._fsync:
                        os.fsync(f.fileno())
            # Rebuild live state from the verified prefix.
            self._open_intents = {}
            self._pending_patches = []
            self._flushed_upto = 0
            self._last_desired = None
            self._tail = []
            self._seq = last_seq
            for rec in records:
                self._apply(rec)
            self.last_replay = {
                "records": len(records),
                "truncated_bytes": truncated,
            }
            return ReplayResult(records, truncated)

    def _quarantine_file(self) -> None:
        try:
            os.replace(self.path, self.path + ".corrupt")
            log.error(
                "intent journal failed closed; corrupt file moved to %s",
                self.path + ".corrupt",
            )
        except OSError as e:
            log.error("could not move corrupt journal aside: %s", e)

    # ---- transaction API --------------------------------------------------

    def begin(self, kind: str, **fields) -> str:
        """Journal an intent BEFORE its first hardware-effecting step;
        returns the transaction id."""
        with self._lock:
            self._txn_counter += 1
            txn = f"{kind}-{self._seq + 1}-{self._txn_counter}"
        self._append(
            {"t": "intent", "txn": txn, "kind": kind,
             "phase": PHASE_BEGUN, **fields}
        )
        return txn

    def mark(self, txn: str, phase: str) -> None:
        self._append({"t": "mark", "txn": txn, "phase": phase})

    def commit(self, txn: str, **fields) -> None:
        self._append({"t": "commit", "txn": txn, **fields})
        self._maybe_compact()

    def abort(self, txn: str, **fields) -> None:
        self._append({"t": "abort", "txn": txn, **fields})
        self._maybe_compact()

    def open_intents(self, kind: str | None = None) -> list[dict]:
        with self._lock:
            intents = sorted(
                self._open_intents.values(), key=lambda r: r["seq"]
            )
            if kind is not None:
                intents = [i for i in intents if i.get("kind") == kind]
            return [dict(i) for i in intents]

    def close_open(self, kind: str, **fields) -> int:
        """Commit every open intent of ``kind`` (e.g. a drain bracket the
        idempotent readmit path just restored). Returns how many closed."""
        closed = 0
        for intent in self.open_intents(kind):
            self.commit(intent["txn"], **fields)
            closed += 1
        return closed

    # ---- desired-mode + deferred patches ---------------------------------

    @property
    def last_desired_mode(self) -> str | None:
        with self._lock:
            return self._last_desired

    def note_desired(self, mode: str) -> None:
        """Remember the desired mode the agent is acting on — boot-time
        local truth while the apiserver is dark. Deduplicated."""
        with self._lock:
            if mode == self._last_desired:
                return
        self._append({"t": "desired", "mode": mode})

    def defer_patch(self, labels: dict) -> None:
        """Journal a node-label write the apiserver refused while
        disconnected; flushed by :meth:`pending_patches` consumers on
        reconnect."""
        self._append({"t": "patch", "labels": dict(labels)})

    def has_pending_patches(self) -> bool:
        with self._lock:
            return bool(self._pending_patches)

    def pending_patches(self) -> dict:
        """The deferred label writes, merged in journal order (last write
        to a key wins — exactly the state the labels would hold had every
        patch landed)."""
        return self.pending_snapshot()[0]

    def pending_snapshot(self) -> tuple[dict, int]:
        """(merged pending patches, max seq included). Flush consumers
        pass that seq to :meth:`patches_flushed` so a patch deferred
        concurrently — AFTER the snapshot — is not marked flushed without
        ever being written."""
        with self._lock:
            merged: dict = {}
            upto = 0
            for rec in self._pending_patches:
                merged.update(rec.get("labels") or {})
                upto = max(upto, rec["seq"])
            return merged, upto

    def patches_flushed(self, upto: int | None = None) -> None:
        if upto is None:
            with self._lock:
                upto = self._seq
        self._append({"t": "flushed", "upto": upto})
        self._maybe_compact()

    # ---- compaction -------------------------------------------------------

    def _maybe_compact(self) -> None:
        with self._lock:
            try:
                if os.path.getsize(self.path) <= self.max_bytes:
                    return
            except OSError:
                return
            try:
                self.compact()
            except JournalError as e:
                # Compaction is an optimization: the triggering append
                # already landed, so its caller must not see a failure.
                # The next intent close retries.
                log.warning("journal compaction failed; will retry: %s", e)

    def compact(self) -> None:
        """Rewrite the journal with only live state (open intents,
        unflushed patches, last desired mode), atomically."""
        with self._lock:
            records: list[dict] = []
            for intent in sorted(
                self._open_intents.values(), key=lambda r: r["seq"]
            ):
                records.append({k: v for k, v in intent.items() if k != "seq"})
            if self._last_desired is not None:
                records.append({"t": "desired", "mode": self._last_desired})
            records.extend(
                {"t": "patch", "labels": rec.get("labels") or {}}
                for rec in self._pending_patches
            )
            tmp = self.path + ".tmp"
            seq = 0
            renumbered: list[dict] = []
            for rec in records:
                seq += 1
                renumbered.append({"seq": seq, **rec})
            try:
                with open(tmp, "wb") as f:
                    f.write(b"".join(_frame(r) for r in renumbered))
                    f.flush()  # drain the BufferedWriter BEFORE the fsync
                    if self._fsync:
                        os.fsync(f.fileno())
                os.replace(tmp, self.path)
                if self._fsync:
                    dir_fd = os.open(
                        os.path.dirname(self.path) or ".", os.O_RDONLY
                    )
                    try:
                        os.fsync(dir_fd)
                    finally:
                        os.close(dir_fd)
            except OSError as e:
                raise JournalError(
                    f"could not compact {self.path}: {e}"
                ) from e
            self._close_fd()
            # Rebuild live state from the renumbered records (semantically
            # unchanged — txn ids are preserved; only seqs moved).
            self._seq = seq
            self._flushed_upto = 0
            self._open_intents = {}
            self._pending_patches = []
            self._tail = []
            for rec in renumbered:
                self._apply(rec)

    # ---- introspection ----------------------------------------------------

    def snapshot(self) -> dict:
        """The live journal as JSON for the /journalz debug endpoint and
        ``tpu-cc-ctl journal``."""
        with self._lock:
            return {
                "path": self.path,
                "seq": self._seq,
                "last_desired_mode": self._last_desired,
                "open_intents": self.open_intents(),
                "pending_patches": self.pending_patches(),
                "pending_patch_records": len(self._pending_patches),
                "last_replay": self.last_replay,
                "recent": [dict(r) for r in self._tail],
            }


class OfflineTracker:
    """Connectivity clock for the disconnected-mode ladder.

    Transport-level apiserver failures (connection resets — a total
    outage's signature) start the clock; any success resets it. Once the
    outage has lasted ``grace_s`` the tracker is *engaged*: the agent
    keeps serving its last-known desired mode and defers label writes
    into the journal instead of failing reconciles against a dead
    control plane. ``grace_s <= 0`` disables engagement entirely.
    """

    def __init__(self, grace_s: float | None = None, clock=time.monotonic):
        if grace_s is None:
            grace_s = float(
                os.environ.get(OFFLINE_GRACE_ENV, str(DEFAULT_OFFLINE_GRACE_S))
            )
        self.grace_s = grace_s
        self._clock = clock
        self._down_since: float | None = None

    def note_failure(self) -> None:
        if self._down_since is None:
            self._down_since = self._clock()

    def note_success(self) -> bool:
        """Returns True when this success ENDED an engaged outage (the
        caller flushes deferred patches on that edge)."""
        was_engaged = self.engaged
        self._down_since = None
        return was_engaged

    @property
    def connected(self) -> bool:
        return self._down_since is None

    @property
    def offline_seconds(self) -> float:
        if self._down_since is None:
            return 0.0
        return max(0.0, self._clock() - self._down_since)

    @property
    def engaged(self) -> bool:
        return self.grace_s > 0 and self.offline_seconds >= self.grace_s


def is_outage_error(e: BaseException) -> bool:
    """Whether an apiserver failure looks like a total outage (transport-
    level: connection refused/reset, no HTTP status). A 403 or 404 is a
    server that answered — not an outage, and never grounds to engage
    disconnected mode."""
    from tpu_cc_manager.kubeclient.api import KubeApiError

    return isinstance(e, KubeApiError) and e.status is None
