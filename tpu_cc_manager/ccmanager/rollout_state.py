"""Crash-safe rollout state: single-writer lease + resumable record.

PR 2/3 made the per-node agents survive crashes and terminal faults; the
rolling orchestrator (ccmanager/rolling.py) was the last component with no
crash story — a bare CLI process whose SIGKILL between windows stranded a
half-flipped pool with no resumable record, and whose concurrent
invocations raced each other's label writes unfenced. This module supplies
both missing properties on top of the kubeclient Lease verbs
(coordination.k8s.io/v1, kubeclient/api.py):

**Single writer with a fencing token.** :class:`RolloutLease` wraps one
Lease object (default ``tpu-operator/tpu-cc-rollout``). Acquisition is a
resourceVersion compare-and-swap: create if absent, else take over only
when the previous holder's ``renewTime + leaseDurationSeconds`` has
passed, bumping ``leaseTransitions`` — which doubles as the **monotonic
fencing token** (the rollout *generation*). A background renewal loop
keeps ``renewTime`` fresh; any CAS loss, holder change, or renewal gap
longer than the lease duration marks the lease **lost**, after which
:class:`FencedKube` refuses every further write with
:class:`RolloutFenced` (counted in ``tpu_cc_rollout_fenced_writes_total``)
— a stale pre-crash orchestrator that wakes up cannot patch a pool a
successor now owns.

**Resumable record.** :class:`RolloutRecord` (mode, selector, generation,
the full ordered group plan, per-group outcomes, failure-budget spend)
is checkpointed into the Lease's ``metadata.annotations`` at every window
boundary — the same CAS write that renews the lease, so a checkpoint from
a fenced-out orchestrator is structurally impossible. A successor reads
the record back during acquisition and resumes exactly where the dead
orchestrator stopped: converged groups are never re-bounced, pre-crash
failures still count against ``--failure-budget``, and quarantined-node
skips are recomputed fresh (ccmanager/rolling.py).

Every desired-mode patch the fenced rollout writes also carries the
generation in :data:`ROLLOUT_GEN_LABEL`, so the pool itself records which
rollout generation last drove each node (``tpu-cc-ctl status``).
"""

from __future__ import annotations

import copy
import hashlib
import json
import logging
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Iterator, Mapping

from tpu_cc_manager import labels as labels_mod
from tpu_cc_manager.kubeclient.api import KubeApi, KubeApiError, WatchEvent
from tpu_cc_manager.utils import metrics as metrics_mod
from tpu_cc_manager.utils import locks as locks_mod
from tpu_cc_manager.utils import retry as retry_mod

log = logging.getLogger(__name__)

#: Where the rollout lease lives. One lease per cluster: a rollout is a
#: pool-level operation and two rollouts racing over overlapping selectors
#: is exactly the hazard the single-writer lock exists to prevent.
LEASE_NAMESPACE_ENV = "CC_ROLLOUT_LEASE_NAMESPACE"
DEFAULT_LEASE_NAMESPACE = "tpu-operator"
LEASE_NAME = "tpu-cc-rollout"

#: Lease annotation carrying the checkpointed rollout record (JSON).
#: Wire names centralized in labels.py (cclint surface contract).
RECORD_ANNOTATION = labels_mod.ROLLOUT_RECORD_ANNOTATION

#: Node label stamped (with the rollout generation) alongside every
#: desired-mode patch a fenced rollout writes.
ROLLOUT_GEN_LABEL = labels_mod.ROLLOUT_GEN_LABEL

DEFAULT_LEASE_DURATION_S = 15.0

RECORD_IN_PROGRESS = "in-progress"
RECORD_COMPLETE = "complete"
RECORD_HALTED = "halted"

#: Checkpoint format version this orchestrator writes. History:
#: 1 (implicit, PR 4): single-shard records with no version field.
#: 2: adds ``version`` and ``wave_shards`` (sharded rollout waves).
#: 3: adds ``surge`` (surge rollouts) — written ONLY when surge > 0, so
#: non-surge records stay v2 and older orchestrators keep resuming them;
#: a surge record resumed by a surge-unaware binary would silently strand
#: the spares' NoSchedule taints, which is exactly the silent field drop
#: the version refusal exists to prevent.
#: 4: adds ``slo_gate`` (SLO-paced rollouts) — written ONLY when a gate
#: is configured, by the same downgrade-compat logic: a latency-gated
#: record resumed by a gate-unaware binary would silently drop the gate
#: and bounce a burning pool at full speed. The parser accepts every
#: version <= the current one — v1 records resume under the sharded
#: orchestrator unchanged (the wave partition is derived from the plan,
#: never persisted) — and refuses newer versions loudly rather than
#: silently dropping fields a successor relied on.
#: 5: adds ``federation`` (region-sharded rollouts) — written ONLY when
#: this record is one regional slice of a MULTI-region federated rollout
#: (ccmanager/federation.py). A federated slice resumed by a
#: federation-unaware binary would re-drive one region unfenced against
#: the GLOBAL failure budget (spending nobody else can see), so v5 is
#: refused loudly by older parsers. A single-region federation is just a
#: plain rollout and serializes <= v4, so it round-trips through the
#: legacy resume path.
#: 6: the ``federation`` dict gains the budget-escrow ledger (``escrow``
#: balance, ``acked_spend``, ``charged`` — parent-plane partition
#: tolerance), written ONLY when the federation has a failure budget to
#: escrow. A v5 binary resuming an escrow-bearing slice would drop the
#: ledger and keep charging while the parent plane is dark with no
#: bound at all — the precise overspend the escrow exists to prevent —
#: so v6 is refused loudly by escrow-unaware parsers; budgetless
#: federated slices stay v5.
#: 7: adds ``ledger`` (the continuous-prestage capacity ledger): every
#: in-flight headroom reservation for a wave-N+1 prestage, plus the
#: per-node charge/release counters that prove exactly-once accounting
#: across a crash. Written ONLY when the ledger has ever been touched. A
#: ledger-unaware binary resuming a v7 record would silently drop the
#: reservations: armed prestages would neither converge against their
#: plan digest nor release their headroom — the successor could stack
#: fresh prestages on top of invisible old ones and spend the knee slack
#: the SLO gate is protecting — so v7 is refused loudly by older
#: parsers. Rollouts that never prestage keep writing <= v6.
#: 8: adds ``failslow`` (journaled fail-slow verdicts): one entry per
#: concluded peer-relative verdict (keyed by the vetter's monotonic id)
#: with the node, the verdict, and whether the orchestrator has ACTED
#: on it yet — journaled behind the ``failslow-vetted`` crash point
#: BEFORE acting, so a SIGKILL mid-containment resumes to the same
#: single quarantine instead of re-deriving (or double-acting) the
#: verdict. Written ONLY when a verdict has been journaled. A
#: failslow-unaware binary resuming a v8 record would drop the acted
#: markers and re-run the ladder from scratch — the double-quarantine
#: the journal exists to prevent — so v8 is refused loudly by older
#: parsers. Rollouts that never concluded a verdict keep writing <= v7.
RECORD_VERSION = 8
#: What records WITHOUT the newer optional fields write (compat floors).
RECORD_VERSION_NO_FAILSLOW = 7
RECORD_VERSION_NO_LEDGER = 6
RECORD_VERSION_NO_ESCROW = 5
RECORD_VERSION_NO_FEDERATION = 4
RECORD_VERSION_NO_SLO = 3
RECORD_VERSION_NO_SURGE = 2

#: Capacity-ledger entry states. ``reserved``: headroom charged, the
#: arm annotation not yet (durably) written. ``armed``: the PRESTAGE
#: annotation is on the node; its agent is (or will be) running the
#: full journaled flip + warmup — the node is in transition and
#: consumes headroom. ``held``: the agent published a valid prestaged
#: record and re-admitted — the node serves again (at the target mode,
#: holding), so it no longer consumes transition headroom; the entry
#: stays until the node's flip window converges it (release) or the
#: plan moves past it (invalidate).
LEDGER_RESERVED = "reserved"
LEDGER_ARMED = "armed"
LEDGER_HELD = "held"
_LEDGER_STATES = (LEDGER_RESERVED, LEDGER_ARMED, LEDGER_HELD)


def plan_digest(mode: str, gid: str, names) -> str:
    """Short content digest of one group's flip plan (target mode +
    group identity + membership). A ledger entry is only adoptable while
    the digest it was reserved under still matches the live plan — a
    stale prestaged node must re-flip, never converge against an old
    plan (rolling.py continuous prestage)."""
    basis = "|".join([str(mode), str(gid)] + sorted(str(n) for n in names))
    return hashlib.sha256(basis.encode("utf-8")).hexdigest()[:12]


@dataclass
class CapacityLedger:
    """Journaled headroom reservations for continuous prestage (record
    format v7). One entry per prestaging node; ``charged``/``released``
    are per-node lifetime counters, persisted so "balances to zero, no
    double charge" is provable across a crash: the ledger is balanced
    iff total charges minus total releases equals the live entry count,
    and a node was never double-charged iff its charge count stayed at
    one. All mutation happens under the orchestrator's record lock
    (rolling.py brackets every mutation + checkpoint)."""

    entries: dict[str, dict] = field(default_factory=dict)
    charged: dict[str, int] = field(default_factory=dict)
    released: dict[str, int] = field(default_factory=dict)

    def entry(self, node: str) -> dict | None:
        return self.entries.get(node)

    def in_transition(self) -> int:
        """Entries currently consuming headroom (reserved/armed — the
        node is mid-prestage). Held entries serve again and count 0."""
        return sum(
            1 for e in self.entries.values()
            if e.get("state") != LEDGER_HELD
        )

    def active(self) -> int:
        return len(self.entries)

    def reserve(
        self, node: str, gid: str, digest: str, generation: int,
        limit: int,
    ) -> bool:
        """CAS-reserve one node of headroom. Refused (False, nothing
        charged) when the node already holds an entry — re-reserving is
        the double charge the ledger exists to prevent; a resume adopts
        the existing entry instead — or when the reservation would push
        the in-transition count past ``limit``. The caller checkpoints
        the record after a successful reserve: the durable write IS the
        reservation."""
        if node in self.entries:
            return False
        if self.in_transition() >= max(0, int(limit)):
            return False
        self.entries[node] = {
            "gid": str(gid),
            "digest": str(digest),
            "generation": int(generation),
            "state": LEDGER_RESERVED,
        }
        self.charged[node] = self.charged.get(node, 0) + 1
        return True

    def mark(self, node: str, state: str, generation: int | None = None) -> None:
        """Advance an entry's state (reserved -> armed -> held). A
        resume re-stamps the fence generation it adopted the entry
        under."""
        assert state in _LEDGER_STATES, state
        e = self.entries.get(node)
        if e is None:
            return
        e["state"] = state
        if generation is not None:
            e["generation"] = int(generation)

    def release(self, node: str) -> bool:
        """Drop an entry (converged / invalidated / aborted / degraded)
        and count the release. Releasing an absent node is a no-op
        (False) so the counters can never drift from the entry map — a
        crash between an in-memory release and its checkpoint re-runs
        the release idempotently on resume."""
        if self.entries.pop(node, None) is None:
            return False
        self.released[node] = self.released.get(node, 0) + 1
        return True

    def charges_total(self) -> int:
        return sum(self.charged.values())

    def releases_total(self) -> int:
        return sum(self.released.values())

    def balanced(self) -> bool:
        """The conservation invariant: every charge is either still an
        entry or exactly one release. Zero entries + balanced means the
        ledger balances to zero."""
        return (
            self.charges_total() - self.releases_total()
            == len(self.entries)
        )

    def double_charged(self) -> list[str]:
        """Nodes charged more than once over the rollout's lifetime —
        must stay empty across any kill/resume interleaving."""
        return sorted(n for n, c in self.charged.items() if c > 1)

    def to_dict(self) -> dict:
        return {
            "entries": {n: dict(e) for n, e in sorted(self.entries.items())},
            "charged": dict(sorted(self.charged.items())),
            "released": dict(sorted(self.released.items())),
        }

    @classmethod
    def from_dict(cls, obj: dict) -> "CapacityLedger":
        return cls(
            entries={
                str(n): dict(e)
                for n, e in (obj.get("entries") or {}).items()
            },
            charged={
                str(n): int(c)
                for n, c in (obj.get("charged") or {}).items()
            },
            released={
                str(n): int(c)
                for n, c in (obj.get("released") or {}).items()
            },
        )

    def touched(self) -> bool:
        """Whether this ledger has ever recorded anything — an untouched
        ledger is dropped from the serialized record so non-prestaging
        rollouts keep their downgrade-compatible <= v6 format."""
        return bool(self.entries or self.charged)


def lease_namespace() -> str:
    return os.environ.get(LEASE_NAMESPACE_ENV, DEFAULT_LEASE_NAMESPACE)


class RolloutFenced(Exception):
    """This orchestrator no longer holds the rollout lease: a successor
    (or expiry) fenced it out, and it must stop writing immediately."""


class LeaseHeld(Exception):
    """Another live orchestrator holds the rollout lease."""

    def __init__(self, holder: str, renew_age_s: float | None = None):
        age = (
            f", last renewed {renew_age_s:.0f}s ago"
            if renew_age_s is not None
            else ""
        )
        super().__init__(f"rollout lease held by {holder!r}{age}")
        self.holder = holder


@dataclass
class RolloutRecord:
    """The durable state of one pool rollout (JSON in the lease
    annotation). ``groups`` is the FULL ordered plan decided at start;
    ``done`` maps finished group ids to their outcome; ``budget_spend``
    is the set of node names already charged against ``failure_budget``
    (quarantined-or-failed), which must survive a crash so a successor's
    budget math starts from the pre-crash spend, not from zero."""

    mode: str
    selector: str
    generation: int
    groups: list[tuple[str, tuple[str, ...]]]
    done: dict[str, dict] = field(default_factory=dict)
    budget_spend: list[str] = field(default_factory=list)
    max_unavailable: int = 1
    failure_budget: int | None = None
    status: str = RECORD_IN_PROGRESS
    # Sharded rollout waves (format v2): how many concurrent lease-fenced
    # sub-rollouts the recording orchestrator ran; a plain resume inherits
    # it like max_unavailable/failure_budget.
    wave_shards: int = 1
    # Surge rollouts (format v3, written only when non-zero): how many
    # spare nodes the recording orchestrator flipped first behind the
    # surge taint. Carried for visibility and for the resume's stale-
    # taint reclaim — a resume never re-runs the surge phase itself
    # (rolling.py: re-picking "spares" from serving nodes would exceed
    # max_unavailable behind a taint that evicts nothing).
    surge: int = 0
    # SLO-paced rollouts (format v4, written only when configured): the
    # gate's parameters (rolling.SloGateConfig.to_dict() — max burn
    # rate, p99 target, window, pause budget, metrics source), persisted
    # so a crash + --resume re-arms the gate instead of silently
    # resuming a latency-gated rollout ungated.
    slo_gate: dict | None = None
    # Federated region-sharded rollouts (format v5, written only for a
    # regional slice of a MULTI-region federation): this shard's region
    # name plus the parent-record coordinates
    # (ccmanager/federation.py FederationGate.to_record_dict()) so a
    # crash + --resume reconnects the successor to the parent's global
    # budget instead of silently resuming one region unfenced.
    federation: dict | None = None
    # Continuous-prestage capacity ledger (format v7, written only once
    # touched): in-flight wave-N+1 headroom reservations plus the
    # per-node charge/release counters. A successor adopts armed
    # entries as-is (no re-surge, no second charge) and invalidates
    # entries whose plan digest no longer matches.
    ledger: CapacityLedger | None = None
    # Journaled fail-slow verdicts (format v8, written only when one
    # exists): vetter verdict id (str) -> {"node", "verdict",
    # "deviation", "acted"}. Journal-then-act: an entry lands here (and
    # is checkpointed) BEFORE the remediation ladder runs, behind the
    # failslow-vetted crash point, so a successor acts each verdict
    # exactly once — already-acted entries are skipped, unacted ones
    # retried (the ladder's actions are idempotent).
    failslow: dict[str, dict] = field(default_factory=dict)

    def charge_budget(self, nodes) -> None:
        self.budget_spend = sorted(set(self.budget_spend) | set(nodes))

    def note_group(
        self, gid: str, ok: bool, states: dict, seconds: float,
        skipped: bool = False,
    ) -> None:
        self.done[gid] = {
            "ok": bool(ok),
            "states": dict(states),
            "seconds": round(float(seconds), 3),
            "skipped": bool(skipped),
        }

    def to_json(self) -> str:
        # A single-region "federation" is a plain rollout: drop the field
        # so the record stays <= v4 and the legacy resume path round-trips
        # it (the downgrade-compat contract, tests/test_federation.py).
        federation = self.federation if (
            self.federation and int(self.federation.get("regions") or 0) > 1
        ) else None
        ledger = (
            self.ledger if self.ledger is not None and self.ledger.touched()
            else None
        )
        if self.failslow:
            # A verdict is journaled: a failslow-unaware resume would
            # drop the acted markers and double-act the ladder, so
            # refuse downgrade.
            version = RECORD_VERSION
        elif ledger is not None:
            # The rollout prestaged: a ledger-unaware resume would drop
            # the reservations and stack fresh prestages on invisible
            # old ones, so refuse downgrade.
            version = RECORD_VERSION_NO_FAILSLOW
        elif federation and "escrow" in federation:
            # The shard holds an escrow ledger (parent-plane partition
            # tolerance): an escrow-unaware resume would keep charging
            # unbounded while the parent is dark, so refuse downgrade.
            version = RECORD_VERSION_NO_LEDGER
        elif federation:
            version = RECORD_VERSION_NO_ESCROW
        elif self.slo_gate:
            version = RECORD_VERSION_NO_FEDERATION
        elif self.surge:
            version = RECORD_VERSION_NO_SLO
        else:
            version = RECORD_VERSION_NO_SURGE
        body = {
            "version": version,
            "mode": self.mode,
            "selector": self.selector,
            "generation": self.generation,
            "groups": [[gid, list(nodes)] for gid, nodes in self.groups],
            "done": self.done,
            "budget_spend": list(self.budget_spend),
            "max_unavailable": self.max_unavailable,
            "failure_budget": self.failure_budget,
            "status": self.status,
            "wave_shards": self.wave_shards,
            "surge": self.surge,
            "slo_gate": self.slo_gate,
        }
        if federation:
            body["federation"] = federation
        if ledger is not None:
            body["ledger"] = ledger.to_dict()
        if self.failslow:
            body["failslow"] = self.failslow
        return json.dumps(body, sort_keys=True, separators=(",", ":"))

    @classmethod
    def from_json(cls, data: str) -> "RolloutRecord":
        try:
            obj = json.loads(data)
            version = int(obj.get("version") or 1)
            if version > RECORD_VERSION:
                # A newer orchestrator checkpointed fields this one cannot
                # represent; resuming would silently drop them.
                raise RolloutFenced(
                    f"rollout record format v{version} is newer than this "
                    f"orchestrator understands (max v{RECORD_VERSION}); "
                    "upgrade, or --abort to discard"
                )
            return cls(
                mode=str(obj["mode"]),
                selector=str(obj["selector"]),
                generation=int(obj["generation"]),
                groups=[
                    (str(gid), tuple(str(n) for n in nodes))
                    for gid, nodes in obj["groups"]
                ],
                done={str(k): dict(v) for k, v in (obj.get("done") or {}).items()},
                budget_spend=[str(n) for n in obj.get("budget_spend") or []],
                max_unavailable=int(obj.get("max_unavailable") or 1),
                failure_budget=(
                    int(obj["failure_budget"])
                    if obj.get("failure_budget") is not None
                    else None
                ),
                status=str(obj.get("status") or RECORD_IN_PROGRESS),
                wave_shards=int(obj.get("wave_shards") or 1),
                surge=int(obj.get("surge") or 0),
                slo_gate=(
                    dict(obj["slo_gate"])
                    if isinstance(obj.get("slo_gate"), dict) else None
                ),
                federation=(
                    dict(obj["federation"])
                    if isinstance(obj.get("federation"), dict) else None
                ),
                ledger=(
                    CapacityLedger.from_dict(obj["ledger"])
                    if isinstance(obj.get("ledger"), dict) else None
                ),
                failslow=(
                    {str(k): dict(v) for k, v in obj["failslow"].items()}
                    if isinstance(obj.get("failslow"), dict) else {}
                ),
            )
        except RolloutFenced:
            raise
        except (ValueError, KeyError, TypeError) as e:
            raise RolloutFenced(f"unreadable rollout record: {e}") from e


def record_of_lease(lease: dict) -> RolloutRecord | None:
    """Parse the checkpointed record out of a Lease object (None when the
    annotation is absent). An unreadable record raises RolloutFenced — a
    corrupt checkpoint must be surfaced, not silently restarted over."""
    raw = ((lease.get("metadata") or {}).get("annotations") or {}).get(
        RECORD_ANNOTATION
    )
    return RolloutRecord.from_json(raw) if raw else None


def _now_rfc3339(wall) -> str:
    # divmod AFTER scaling to whole microseconds: rounding the fraction
    # alone can yield 1000000 µs (a 7-digit field a real apiserver's
    # MicroTime parser rejects) when the wall clock sits within half a
    # microsecond of the next second.
    secs, micros = divmod(int(round(wall() * 1e6)), 1_000_000)
    return time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(secs)) + (
        ".%06dZ" % micros
    )


def _parse_rfc3339(value: str | None) -> float | None:
    if not value:
        return None
    try:
        base, _, frac = value.rstrip("Z").partition(".")
        import calendar

        stamp = calendar.timegm(time.strptime(base, "%Y-%m-%dT%H:%M:%S"))
        return stamp + (float("0." + frac) if frac else 0.0)
    except (ValueError, OverflowError):
        return None


def lease_holder_alive(lease: dict, wall=time.time) -> tuple[str | None, bool]:
    """(holderIdentity or None, whether that hold is still live) for a
    Lease object — the shared expiry predicate for status display and the
    --abort live-holder guard."""
    spec = lease.get("spec") or {}
    holder = spec.get("holderIdentity") or None
    if holder is None:
        return None, False
    renew = _parse_rfc3339(spec.get("renewTime") or spec.get("acquireTime"))
    duration = float(spec.get("leaseDurationSeconds") or 0)
    return holder, renew is not None and (wall() - renew) < duration


def release_lease(api: KubeApi, namespace: str, name: str = LEASE_NAME) -> None:
    """Force-release: empty the holder and discard the record via CAS
    update — NOT delete. Keeping the Lease object preserves the
    ``leaseTransitions`` counter, so the fencing generation stays
    monotonic across an abort (a deleted-and-recreated lease would
    restart at 1 and the rollout-gen labels would go backwards). A live
    wedged holder's next renewal 409s against this write, re-reads a
    holder that is no longer it, and fences itself immediately."""
    for _ in range(4):
        lease = api.get_lease(namespace, name)
        lease["spec"]["holderIdentity"] = ""
        ((lease.get("metadata") or {}).get("annotations") or {}).pop(
            RECORD_ANNOTATION, None
        )
        try:
            api.update_lease(namespace, name, lease)
            return
        except KubeApiError as e:
            if e.status != 409:
                raise
    raise KubeApiError(
        None, f"lease {namespace}/{name}: force-release kept conflicting"
    )


def describe_lease(lease: dict, wall=time.time) -> str:
    """One operator-readable line about the rollout lease + record, for
    ``tpu-cc-ctl status``: who holds it, whether the hold is live or
    expired (resumable), the fencing generation, and groups done/total."""
    spec = lease.get("spec") or {}
    holder = spec.get("holderIdentity") or "-"
    renew = _parse_rfc3339(spec.get("renewTime") or spec.get("acquireTime"))
    duration = float(spec.get("leaseDurationSeconds") or 0)
    if not holder or holder == "-":
        liveness = "released"
    elif renew is None or wall() - renew >= duration:
        liveness = "EXPIRED (resumable)"
    else:
        liveness = f"live, renewed {wall() - renew:.0f}s ago"
    parts = [
        f"holder={holder}", f"({liveness})",
        f"generation={spec.get('leaseTransitions', '?')}",
    ]
    try:
        record = record_of_lease(lease)
    except RolloutFenced:
        record = None
        parts.append("record=UNREADABLE")
    if record is not None:
        done_ok = sum(1 for d in record.done.values() if d.get("ok"))
        parts.insert(0, f"mode={record.mode} selector={record.selector}")
        parts.append(f"groups={done_ok}/{len(record.groups)} done")
        parts.append(f"status={record.status}")
    return "ROLLOUT " + " ".join(parts)


class RolloutLease:
    """One orchestrator's hold on the rollout lease.

    ``wall`` (epoch seconds, for the cross-process expiry decision baked
    into the Lease object) and ``clock`` (monotonic, for this process's
    own validity window) are injectable so crash/fencing tests control
    time deterministically.
    """

    def __init__(
        self,
        api: KubeApi,
        holder: str,
        namespace: str | None = None,
        name: str = LEASE_NAME,
        duration_s: float = DEFAULT_LEASE_DURATION_S,
        metrics: metrics_mod.MetricsRegistry | None = None,
        wall=time.time,
        clock=time.monotonic,
        max_clock_skew_s: float = 0.0,
    ) -> None:
        self.api = api
        self.holder = holder
        self.namespace = namespace or lease_namespace()
        self.name = name
        self.duration_s = max(0.001, duration_s)
        # Cross-region skew tolerance. When > 0, a wall-clock "expired"
        # verdict against another holder is never trusted directly —
        # their renewTime was stamped by THEIR wall clock, and a skew of
        # ±max_clock_skew_s can fabricate expiry on a healthy holder.
        # Instead acquire() treats renewTime as an opaque token and
        # observes it over one lease duration of LOCAL monotonic time:
        # an alive holder must advance it in that window regardless of
        # what either wall clock reads. 0 keeps the legacy wall-only
        # verdict (single-cluster, one wall clock).
        self.max_clock_skew_s = max(0.0, max_clock_skew_s)
        self.metrics = metrics if metrics is not None else metrics_mod.REGISTRY
        self.wall = wall
        self.clock = clock
        #: The fencing token: leaseTransitions at our acquisition. Every
        #: desired-mode patch carries it; strictly increases across
        #: holders because every acquisition CAS-increments it.
        self.generation: int | None = None
        self.lost = False
        self._lease: dict | None = None  # cclint: guarded-by(_lock)
        self._last_renew: float | None = None  # cclint: guarded-by(_lock)
        self._lock = locks_mod.make_lock("rollout-lease.state")
        # Serializes whole lease WRITES within this process: without it
        # the renewer thread can CAS between the main thread's read and
        # write, turning every window-boundary checkpoint into a
        # conflict. (Cross-process conflicts are still resolved by
        # holder identity + retry in checkpoint().)
        self._write_lock = locks_mod.make_lock("rollout-lease.write")
        self._renew_stop: threading.Event | None = None
        self._renew_thread: threading.Thread | None = None

    # -- acquisition ----------------------------------------------------

    def _expired(self, spec: dict) -> tuple[bool, float | None]:
        renew = _parse_rfc3339(
            spec.get("renewTime") or spec.get("acquireTime")
        )
        if renew is None:
            return True, None  # never renewed / unparseable: claimable
        duration = float(spec.get("leaseDurationSeconds") or self.duration_s)
        age = self.wall() - renew
        return age >= duration, age

    def acquire(self) -> RolloutRecord | None:
        """Create or take over the lease; returns the checkpointed record
        of a previous (dead) holder, or None when starting fresh. Raises
        :class:`LeaseHeld` when a live holder exists, and propagates
        KubeApiError (including the lease-unsupported marker) untouched
        so the caller can degrade."""
        now = _now_rfc3339(self.wall)
        try:
            lease = self.api.get_lease(self.namespace, self.name)
        except KubeApiError as e:
            if e.status != 404:
                raise
            try:
                created = self.api.create_lease(
                    self.namespace, self.name,
                    {
                        "holderIdentity": self.holder,
                        "leaseDurationSeconds": int(round(self.duration_s)) or 1,
                        "acquireTime": now,
                        "renewTime": now,
                        "leaseTransitions": 1,
                    },
                )
            except KubeApiError as e2:
                if e2.status == 409:
                    raise LeaseHeld("<concurrent creator>") from e2
                raise
            with self._lock:
                self._adopt(created, 1)
            log.info(
                "acquired rollout lease %s/%s (generation 1)",
                self.namespace, self.name,
            )
            self.metrics.record_lease_transition()
            return None
        spec = lease.get("spec") or {}
        prev_holder = spec.get("holderIdentity")
        expired, age = self._expired(spec)
        if prev_holder and prev_holder != self.holder:
            # A stamp more than 1 s in OUR future can only come from a
            # skewed remote wall clock; wall math would keep a dead
            # holder "live" until our clock catches up, so it is as
            # suspect as an expired one.
            future_stamp = age is not None and age < -1.0
            if self.max_clock_skew_s > 0 and (expired or future_stamp):
                # The wall clocks disagree about this holder (expired,
                # or stamped from the future) — but their stamp came
                # from a different region's clock, so neither verdict
                # is trustworthy. Confirm skew-free before fencing:
                # watch renewTime as an opaque token for one lease
                # duration of LOCAL monotonic time. An alive holder
                # must advance it; a dead one cannot.
                lease = self._observe_holder(lease, prev_holder)
                spec = lease.get("spec") or {}
            elif not expired:
                raise LeaseHeld(prev_holder, age)
        record = record_of_lease(lease)
        transitions = int(spec.get("leaseTransitions") or 0) + 1
        updated = copy.deepcopy(lease)
        updated["spec"] = {
            "holderIdentity": self.holder,
            "leaseDurationSeconds": int(round(self.duration_s)) or 1,
            "acquireTime": now,
            "renewTime": now,
            "leaseTransitions": transitions,
        }
        try:
            stored = self.api.update_lease(self.namespace, self.name, updated)
        except KubeApiError as e:
            if e.status == 409:
                raise LeaseHeld("<concurrent acquirer>") from e
            raise
        with self._lock:
            self._adopt(stored, transitions)
        log.info(
            "took over rollout lease %s/%s from %r (generation %d%s)",
            self.namespace, self.name, prev_holder, transitions,
            ", resumable record found" if record else "",
        )
        self.metrics.record_lease_transition()
        return record

    def _observe_holder(self, lease: dict, prev_holder: str) -> dict:
        """Skew-free liveness check on another holder: poll the lease for
        one lease duration of LOCAL monotonic time, treating renewTime +
        leaseTransitions purely as an opaque change-token. Any change
        (renewal, or a third party's takeover) proves a live writer →
        :class:`LeaseHeld`; a token frozen for a full duration proves the
        holder dead on ITS OWN terms (it must renew within its advertised
        duration or self-fence) → return the last-seen lease so the
        caller takes over. No wall clock is consulted."""
        spec = lease.get("spec") or {}
        token = (spec.get("renewTime"), spec.get("leaseTransitions"))
        deadline = self.clock() + self.duration_s
        poll = max(0.05, min(1.0, self.duration_s / 5.0))
        while True:
            remaining = deadline - self.clock()
            if remaining <= 0:
                return lease
            retry_mod.wait(min(poll, remaining))
            try:
                lease = self.api.get_lease(self.namespace, self.name)
            except KubeApiError as e:
                if e.status == 404:
                    # Holder (or an aborter) deleted it; acquire() has
                    # already passed the 404 branch, so surface as a
                    # held-then-released race for the caller to retry.
                    raise LeaseHeld(
                        f"{prev_holder!r} (lease deleted mid-observation)"
                    ) from e
                raise
            spec = lease.get("spec") or {}
            now_token = (spec.get("renewTime"), spec.get("leaseTransitions"))
            if now_token != token:
                raise LeaseHeld(
                    spec.get("holderIdentity") or prev_holder,
                )

    def _adopt(self, lease: dict, generation: int) -> None:  # cclint: requires(_lock)
        self._lease = lease
        self.generation = generation
        self._last_renew = self.clock()
        self.lost = False

    # -- validity / fencing ---------------------------------------------

    @property
    def valid(self) -> bool:
        with self._lock:
            return (
                not self.lost
                and self._last_renew is not None
                and (self.clock() - self._last_renew) < self.duration_s
            )

    def check(self) -> None:
        """Raise RolloutFenced unless this process still plausibly holds
        the lease: never explicitly lost AND renewed within the lease
        duration. The time bound is the stale-orchestrator guard — a
        process that slept past its own lease duration must assume a
        successor took over and stop writing, even before any apiserver
        round trip confirms it."""
        if not self.valid:
            self.lost = True
            raise RolloutFenced(
                f"rollout lease {self.namespace}/{self.name} no longer held "
                f"by {self.holder!r} (generation {self.generation})"
            )

    # -- renewal / checkpointing -----------------------------------------

    def checkpoint(self, record: RolloutRecord | None = None,
                   clear_record: bool = False) -> None:
        """Renew the lease and (optionally) persist the rollout record in
        one CAS write. A 409 means someone else wrote the lease; since
        only the holder writes it, that someone is a successor — except
        when a write of OUR OWN landed out from under us (a retried
        ambiguous attempt, or the renewer thread racing across
        processes), which the re-read disambiguates by holder identity.
        In the still-ours case THIS write is retried on the fresh
        resourceVersion — merely adopting the re-read lease would
        silently drop the record update (the conflicting write was
        usually a bare renew), and a successor would then resume from a
        stale checkpoint and re-bounce converged groups."""
        self.check()
        with self._write_lock:
            for _ in range(4):
                with self._lock:
                    lease = copy.deepcopy(self._lease)
                lease["spec"]["renewTime"] = _now_rfc3339(self.wall)
                lease["spec"]["holderIdentity"] = self.holder
                annotations = lease["metadata"].setdefault("annotations", {})
                if record is not None:
                    record.generation = self.generation or record.generation
                    annotations[RECORD_ANNOTATION] = record.to_json()
                elif clear_record:
                    annotations.pop(RECORD_ANNOTATION, None)
                try:
                    stored = self.api.update_lease(
                        self.namespace, self.name, lease
                    )
                except KubeApiError as e:
                    if e.status != 409:
                        raise  # transient apiserver failure: not (yet) fenced
                    resolved = self._resolve_conflict()
                    if resolved is None:
                        raise RolloutFenced(
                            f"rollout lease {self.namespace}/{self.name} was "
                            f"taken over (CAS conflict); {self.holder!r} is "
                            "fenced out"
                        ) from e
                    with self._lock:
                        self._lease = resolved
                        self._last_renew = self.clock()
                    continue  # still ours: retry THIS write on the fresh rv
                with self._lock:
                    self._lease = stored
                    self._last_renew = self.clock()
                return
        # Only reachable if our own writes keep colliding — transient by
        # construction (each round re-read a lease we still hold), so let
        # the caller's retry policy decide.
        raise KubeApiError(
            None,
            f"rollout lease {self.namespace}/{self.name}: checkpoint kept "
            "conflicting with our own writes",
        )

    def _resolve_conflict(self) -> dict | None:
        """After a 409: re-read the lease. Still ours → our earlier write
        landed (adopt it); any other holder → fenced."""
        try:
            stored = self.api.get_lease(self.namespace, self.name)
        except KubeApiError:
            return None  # cannot prove we still hold it: fail safe
        if (stored.get("spec") or {}).get("holderIdentity") == self.holder:
            return stored
        self.lost = True
        return None

    def renew(self) -> None:
        self.checkpoint()

    def start_renewer(self, interval_s: float | None = None) -> None:
        """Background renewal at duration/3 (leader-election convention).
        Transient failures are logged and retried next tick — the local
        validity window in :meth:`check` is what actually fences when
        renewals stop landing."""
        if self._renew_thread is not None:
            return
        interval = interval_s if interval_s is not None else self.duration_s / 3.0
        stop = threading.Event()

        def loop() -> None:
            while not stop.wait(interval):
                try:
                    self.renew()
                except RolloutFenced as e:
                    log.error("rollout lease renewal fenced: %s", e)
                    return
                except KubeApiError as e:
                    log.warning("rollout lease renewal failed: %s", e)

        t = threading.Thread(target=loop, name="rollout-lease-renew", daemon=True)
        self._renew_stop = stop
        self._renew_thread = t
        t.start()

    def stop_renewer(self) -> None:
        if self._renew_stop is not None:
            self._renew_stop.set()
        if self._renew_thread is not None:
            self._renew_thread.join(timeout=2.0)
        self._renew_stop = None
        self._renew_thread = None

    def release(self, clear_record: bool = False) -> None:
        """Give the lease up cleanly (holderIdentity emptied so the next
        orchestrator acquires without waiting out the duration). Best
        effort: a fenced or unreachable lease is simply left to expire."""
        self.stop_renewer()
        if self.lost:
            return
        try:
            self.checkpoint(clear_record=clear_record)
            with self._lock:
                lease = copy.deepcopy(self._lease)
            lease["spec"]["holderIdentity"] = ""
            self.api.update_lease(self.namespace, self.name, lease)
            log.info(
                "released rollout lease %s/%s", self.namespace, self.name
            )
        except (KubeApiError, RolloutFenced) as e:
            log.warning("could not release rollout lease cleanly: %s", e)


class FencedKube(KubeApi):
    """KubeApi wrapper that refuses every WRITE once the rollout lease is
    lost. Reads pass through unfenced — a stale orchestrator looking is
    harmless, a stale orchestrator patching is the split-brain this PR
    exists to prevent. Refusals raise :class:`RolloutFenced` and count in
    ``tpu_cc_rollout_fenced_writes_total``."""

    def __init__(
        self,
        inner: KubeApi,
        lease: RolloutLease,
        metrics: metrics_mod.MetricsRegistry | None = None,
    ) -> None:
        self.inner = inner
        self.lease = lease
        self.metrics = metrics if metrics is not None else lease.metrics
        self.retries_internally = getattr(inner, "retries_internally", False)

    def _fence(self, op: str) -> None:
        try:
            self.lease.check()
        except RolloutFenced:
            self.metrics.record_fenced_write()
            log.error(
                "REFUSED %s: this orchestrator (generation %s) no longer "
                "holds the rollout lease", op, self.lease.generation,
            )
            raise

    # Writes: fenced.

    def patch_node_labels(self, name: str, labels: Mapping[str, str | None]) -> dict:
        self._fence(f"patch_node_labels({name})")
        return self.inner.patch_node_labels(name, labels)

    def patch_node_annotations(
        self, name: str, annotations: Mapping[str, str | None]
    ) -> dict:
        self._fence(f"patch_node_annotations({name})")
        return self.inner.patch_node_annotations(name, annotations)

    def patch_node_taints(
        self, name: str, add: list[dict], remove_keys: list[str]
    ) -> dict:
        self._fence(f"patch_node_taints({name})")
        return self.inner.patch_node_taints(name, add, remove_keys)

    # Reads and best-effort signals: pass through.

    def get_node(self, name: str) -> dict:
        return self.inner.get_node(name)

    def list_nodes(self, label_selector: str | None = None) -> list[dict]:
        return self.inner.list_nodes(label_selector)

    def list_nodes_page(
        self,
        label_selector: str | None = None,
        limit: int | None = None,
        continue_token: str | None = None,
    ) -> dict:
        return self.inner.list_nodes_page(label_selector, limit, continue_token)

    def watch_nodes_pool(
        self,
        label_selector: str | None = None,
        resource_version: str | None = None,
        timeout_seconds: int = 300,
    ) -> Iterator[WatchEvent]:
        return self.inner.watch_nodes_pool(
            label_selector, resource_version, timeout_seconds
        )

    def list_pods(
        self,
        namespace: str,
        label_selector: str | None = None,
        field_selector: str | None = None,
    ) -> list[dict]:
        return self.inner.list_pods(namespace, label_selector, field_selector)

    def watch_nodes(
        self,
        name: str,
        resource_version: str | None = None,
        timeout_seconds: int = 300,
    ) -> Iterator[WatchEvent]:
        return self.inner.watch_nodes(name, resource_version, timeout_seconds)

    def create_event(self, namespace: str, event: dict) -> dict:
        return self.inner.create_event(namespace, event)

    def self_subject_access_review(
        self, verb: str, resource: str, namespace: str | None = None
    ) -> bool:
        return self.inner.self_subject_access_review(verb, resource, namespace)

    def get_lease(self, namespace: str, name: str) -> dict:
        return self.inner.get_lease(namespace, name)

    def create_lease(self, namespace: str, name: str, spec: dict) -> dict:
        return self.inner.create_lease(namespace, name, spec)

    def update_lease(self, namespace: str, name: str, lease: dict) -> dict:
        return self.inner.update_lease(namespace, name, lease)

    def delete_lease(self, namespace: str, name: str) -> None:
        return self.inner.delete_lease(namespace, name)
