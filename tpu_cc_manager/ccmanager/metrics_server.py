"""Tiny Prometheus text endpoint (stdlib http.server, daemon thread).

The reference has no metrics endpoint (SURVEY.md §5 — its only outward state
is node labels and a readiness file). Since this build's north-star is a
latency, the phase timings in utils/metrics.py are exported at
``/metrics``; ``/healthz`` returns 200 for liveness probes.
"""

from __future__ import annotations

import http.server
import logging
import os
import threading

from tpu_cc_manager.utils.metrics import MetricsRegistry

log = logging.getLogger(__name__)


def start_metrics_server(
    port: int, registry: MetricsRegistry, bind: str | None = None
) -> http.server.ThreadingHTTPServer:
    """Serve /metrics and /healthz on ``bind``:``port``.

    The endpoint is unauthenticated (Prometheus-style). The default bind
    IS all-interfaces (0.0.0.0) — inside a pod that is the pod IP, which
    kubelet probes and the scraper must reach. Operators running the
    agent on a host network should restrict it via CC_METRICS_BIND
    (e.g. 127.0.0.1) or the ``bind`` argument."""
    if bind is None:
        bind = os.environ.get("CC_METRICS_BIND", "0.0.0.0")
    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 - http.server API
            if self.path.rstrip("/") in ("", "/metrics"):
                body = registry.render_prometheus().encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain; version=0.0.4")
            elif self.path == "/healthz":
                body = b"ok\n"
                self.send_response(200)
                self.send_header("Content-Type", "text/plain")
            else:
                body = b"not found\n"
                self.send_response(404)
                self.send_header("Content-Type", "text/plain")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, fmt, *fmtargs):  # quiet access logs
            log.debug("metrics http: " + fmt, *fmtargs)

    server = http.server.ThreadingHTTPServer((bind, port), Handler)
    thread = threading.Thread(target=server.serve_forever, name="metrics", daemon=True)
    thread.start()
    # server_address, not the requested port: port=0 binds an ephemeral
    # one and the log is how it's discovered.
    log.info("metrics server listening on %s:%d", bind, server.server_address[1])
    return server
