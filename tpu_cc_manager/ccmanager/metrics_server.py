"""Tiny Prometheus text + debug endpoint server (stdlib, daemon thread).

The reference has no metrics endpoint (SURVEY.md §5 — its only outward state
is node labels and a readiness file). Since this build's north-star is a
latency, the phase timings in utils/metrics.py are exported at
``/metrics``; ``/healthz`` returns 200 for liveness probes; and the tracing
subsystem (obs/) is served at two debug endpoints:

- ``/statusz`` — JSON: mode/result of the last reconcile, its per-phase
  seconds and trace id, cumulative result totals, and the in-flight span
  tree (what the agent is doing *right now*, nested);
- ``/tracez`` — JSON: recent finished spans from the journal ring,
  filterable by ``?trace_id=`` (returns that trace's spans plus their
  nested tree) and boundable by ``?limit=``;
- ``/journalz`` — JSON: the live node-local intent journal
  (ccmanager/intent_journal.py): open intents, deferred label patches,
  last replay outcome — what ``tpu-cc-ctl journal <node>`` reads;
- ``/rolloutz`` — JSON: the rollout flight recorder's live snapshot
  (obs/flight.py): generation, trace id, recent decision events, torn-
  line count — the orchestrator's (``ctl rollout --metrics-port``) and
  the serve harness's mid-rollout observability surface.
"""

from __future__ import annotations

import http.server
import json
import logging
import os
import threading
import time
from urllib.parse import parse_qs, urlparse

from tpu_cc_manager.obs import journal as journal_mod
from tpu_cc_manager.utils.metrics import MetricsRegistry
from tpu_cc_manager.version import __version__

log = logging.getLogger(__name__)

# /tracez default and ceiling for ?limit= (the ring itself bounds memory;
# this bounds one response).
TRACEZ_DEFAULT_LIMIT = 256
TRACEZ_MAX_LIMIT = 4096


def _statusz_payload(
    registry: MetricsRegistry, journal: journal_mod.Journal
) -> dict:
    last = registry.last()
    last_reconcile = None
    if last is not None:
        last_reconcile = {
            "mode": last.mode,
            "result": last.result,
            "trace_id": last.trace_id,
            "total_seconds": round(last.total_seconds, 3),
            "phases": {p.name: round(p.seconds, 3) for p in last.phases},
        }
    active = journal.active_spans()
    finished = journal.spans()
    totals = registry.result_totals()
    return {
        # For the fleet gateway (obs/fleet.py): agent_version identifies
        # mixed-version fleets mid-rollout; snapshot_ts is MONOTONIC and
        # stamped per response, so a scrape whose snapshot_ts fails to
        # advance between sweeps is a cached/replayed body from a dead
        # agent — stale, not live.
        "agent_version": __version__,
        "snapshot_ts": round(time.monotonic(), 6),
        "mode": last.mode if last is not None else None,
        "reconciling": bool(
            last is not None and last.result == "pending"
        ),
        "last_reconcile": last_reconcile,
        "in_flight": journal.span_tree(active),
        "result_totals": {
            r: totals.get(r, 0) for r in ("ok", "failed", "noop")
        },
        "failure_totals": registry.failure_totals(),
        "journal_spans": len(finished),
        "journal_traces": len(
            {s["trace_id"] for s in finished}
        ),
    }


def _tracez_payload(journal: journal_mod.Journal, query: dict) -> dict:
    trace_id = (query.get("trace_id") or [None])[0]
    try:
        limit = int((query.get("limit") or [str(TRACEZ_DEFAULT_LIMIT)])[0])
    except ValueError:
        limit = TRACEZ_DEFAULT_LIMIT
    limit = max(1, min(limit, TRACEZ_MAX_LIMIT))
    spans = journal.spans(trace_id=trace_id, limit=limit)
    payload: dict = {
        "trace_id": trace_id,
        "count": len(spans),
        "spans": spans,
    }
    if trace_id is not None:
        # One trace fits in one response; nest it for human consumption.
        payload["tree"] = journal.span_tree(spans)
    else:
        payload["trace_ids"] = journal.trace_ids()[-limit:]
    return payload


def start_metrics_server(
    port: int,
    registry: MetricsRegistry,
    bind: str | None = None,
    journal: journal_mod.Journal | None = None,
    intent_journal=None,
    flight=None,
) -> http.server.ThreadingHTTPServer:
    """Serve /metrics, /healthz, /statusz, /tracez and /rolloutz on
    ``bind``:``port``.

    The endpoint is unauthenticated (Prometheus-style). The default bind
    IS all-interfaces (0.0.0.0) — inside a pod that is the pod IP, which
    kubelet probes and the scraper must reach. Operators running the
    agent on a host network should restrict it via CC_METRICS_BIND
    (e.g. 127.0.0.1) or the ``bind`` argument."""
    if bind is None:
        bind = os.environ.get("CC_METRICS_BIND", "0.0.0.0")
    if journal is None:
        journal = journal_mod.JOURNAL
    jnl = journal

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 - http.server API
            url = urlparse(self.path)
            path = url.path.rstrip("/")
            content_type = "application/json"
            if path in ("", "/metrics"):
                body = registry.render_prometheus().encode()
                content_type = "text/plain; version=0.0.4"
                code = 200
            elif path == "/healthz":
                body = b"ok\n"
                content_type = "text/plain"
                code = 200
            elif path == "/statusz":
                body = (
                    json.dumps(_statusz_payload(registry, jnl), indent=1)
                    + "\n"
                ).encode()
                code = 200
            elif path == "/tracez":
                body = (
                    json.dumps(
                        _tracez_payload(jnl, parse_qs(url.query)), indent=1
                    )
                    + "\n"
                ).encode()
                code = 200
            elif path == "/journalz":
                payload = (
                    intent_journal.snapshot()
                    if intent_journal is not None
                    else {"enabled": False}
                )
                body = (json.dumps(payload, indent=1) + "\n").encode()
                code = 200
            elif path == "/rolloutz":
                payload = (
                    flight.snapshot()
                    if flight is not None
                    else {"enabled": False}
                )
                body = (json.dumps(payload, indent=1) + "\n").encode()
                code = 200
            else:
                body = b"not found\n"
                content_type = "text/plain"
                code = 404
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, fmt, *fmtargs):  # quiet access logs
            log.debug("metrics http: " + fmt, *fmtargs)

    server = http.server.ThreadingHTTPServer((bind, port), Handler)
    thread = threading.Thread(target=server.serve_forever, name="metrics", daemon=True)
    thread.start()
    # server_address, not the requested port: port=0 binds an ephemeral
    # one and the log is how it's discovered.
    log.info("metrics server listening on %s:%d", bind, server.server_address[1])
    return server
