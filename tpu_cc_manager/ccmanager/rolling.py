"""Rolling CC reconfiguration across a node pool.

New logic with no reference counterpart (SURVEY.md §7.8: "the reference is
purely per-node independent; rolling coordination across a pool is new").
Drives BASELINE.json configs[3] — flip a whole v5p-32 pool to CC-on under a
live training job — by setting each node's desired-mode label and waiting
for the per-node agents (the DaemonSet) to converge, with:

- **slice grouping**: multi-host slices are bounced as one unit, because a
  TPU slice is unusable while *any* of its hosts is down (SURVEY.md §7
  hard part (a)) — bouncing its hosts one at a time would just multiply the
  disruption window;
- **bounded concurrency**: at most ``max_unavailable`` groups in flight
  (PodDisruptionBudget-style, default 1 — strictly rolling);
- **failure policy**: a node converging to ``failed`` halts the rollout by
  default (``continue_on_failure`` to override);
- per-group latency records for the <90 s/node north-star accounting;
- **crash safety** (ccmanager/rollout_state.py): when constructed with a
  :class:`~tpu_cc_manager.ccmanager.rollout_state.RolloutLease`, every
  write is fenced by the lease (a stale orchestrator's patches are
  refused), desired-mode patches carry the rollout generation, the plan
  and per-group progress are checkpointed into the lease at every window
  boundary, and a successor constructed with the persisted
  ``resume_record`` picks up exactly where a dead orchestrator stopped:
  converged groups are never re-bounced, pre-crash failures still count
  against the failure budget, quarantined-node skips are recomputed
  fresh.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import statistics
import threading
import time

from tpu_cc_manager.kubeclient.api import (
    KubeApi,
    KubeApiError,
    caller_retry_attempts,
    classify_kube_error,
    node_labels,
)
from tpu_cc_manager import labels as labels_mod
from tpu_cc_manager.labels import (
    CC_MODE_LABEL,
    CC_MODE_STATE_LABEL,
    STATE_FAILED,
    VALID_MODES,
    canonical_mode,
)

from tpu_cc_manager.labels import SLICE_ID_LABEL  # noqa: F401 - re-export
from tpu_cc_manager.ccmanager import rollout_state
from tpu_cc_manager.obs import flight as flight_mod
from tpu_cc_manager.obs import trace as obs_trace
from tpu_cc_manager.utils import metrics as metrics_mod
from tpu_cc_manager.utils import retry as retry_mod
from tpu_cc_manager.utils import locks as locks_mod

log = logging.getLogger(__name__)


@dataclasses.dataclass
class GroupResult:
    group: str
    nodes: tuple[str, ...]
    ok: bool
    seconds: float
    states: dict[str, str]
    # Already at the target (desired AND reported state) when the rollout
    # started — e.g. converged by an interrupted earlier rollout. Skipped
    # idempotently: no label rewrite, no bounce, no await.
    skipped: bool = False


@dataclasses.dataclass
class RolloutResult:
    mode: str
    ok: bool
    groups: list[GroupResult]
    # Wall-clock per concurrency window (groups inside a window run in
    # parallel, so their per-group durations overlap; only window times sum
    # to the rollout's wall time).
    window_seconds: list[float] = dataclasses.field(default_factory=list)
    # Groups reverted to their pre-rollout desired mode after a failure
    # halt (rollback_on_failure).
    rolled_back: list[GroupResult] = dataclasses.field(default_factory=list)
    # Quarantined nodes excluded from the rollout (remediation ladder).
    skipped_quarantined: list[str] = dataclasses.field(default_factory=list)
    # Why the rollout halted before finishing ("failure-budget-exceeded"
    # for the pool-level circuit breaker; None otherwise — a plain group
    # failure reads from ok/groups as before).
    halted_reason: str | None = None
    # Crash-safe orchestration (rollout_state.py): whether this run
    # resumed a dead orchestrator's persisted record, and the fencing
    # generation its writes carried.
    resumed: bool = False
    generation: int | None = None
    # Autoscaler interplay: nodes whose Node object vanished mid-rollout
    # (scale-down; retired, never charged) and nodes created mid-rollout
    # that matched the selector and were adopted into a trailing wave.
    retired_deleted: list[str] = dataclasses.field(default_factory=list)
    adopted: list[str] = dataclasses.field(default_factory=list)
    # Surge rollouts: the spare nodes flipped first behind the surge
    # taint, and the highest concurrently-disrupted (non-surge) group
    # count observed — the measured pool unavailability, which must stay
    # <= max_unavailable per wave throughout a surge rollout.
    surged: list[str] = dataclasses.field(default_factory=list)
    max_unavailable_observed: int = 0

    @property
    def seconds(self) -> float:
        return sum(self.window_seconds)

    def summary(self) -> dict:
        converged = [g for g in self.groups if g.ok]
        converged_nodes = sum(len(g.nodes) for g in converged)
        return {
            "mode": self.mode,
            "ok": self.ok,
            "halted": self.halted_reason,
            "resumed": self.resumed or None,
            "generation": self.generation,
            "quarantined_skipped": self.skipped_quarantined or None,
            "groups": len(self.groups),
            "skipped_groups": sum(1 for g in self.groups if g.skipped) or None,
            "nodes": sum(len(g.nodes) for g in self.groups),
            "total_seconds": round(self.seconds, 2),
            "max_group_seconds": round(
                max((g.seconds for g in self.groups), default=0.0), 2
            ),
            "mean_seconds_per_node": round(
                self.seconds / converged_nodes, 2
            ) if converged_nodes and self.ok else None,
            "retired_deleted": self.retired_deleted or None,
            "adopted": self.adopted or None,
            "surged": self.surged or None,
            "max_unavailable_observed": (
                self.max_unavailable_observed or None
            ),
            # Per-group revert outcome: a rollback that itself failed or
            # timed out must not read as "safely restored", and one that
            # could not be awaited (prior label absent → default mode
            # depends on host capability) must not read success-shaped.
            "rolled_back": {
                g.group: (
                    "unverified"
                    if any(
                        s == "reverted-unawaited" for s in g.states.values()
                    )
                    else ("ok" if g.ok else "failed")
                )
                for g in self.rolled_back
            } or None,
        }


#: Well-known zone label (topology.kubernetes.io/zone): the natural
#: failure-domain boundary for sharded rollout waves — bouncing every
#: zone's nodes from one serial queue wastes exactly the independence
#: zones exist to provide.
ZONE_LABEL = "topology.kubernetes.io/zone"

#: NoSchedule taint carried by surge spares while they flip: the node is
#: unschedulable-for-workloads for exactly the flip window, so the flip
#: never subtracts from the pool's serving capacity. Removed ("reclaimed")
#: the moment the spare converges, at which point it can absorb the
#: workloads the regular waves drain off the rest of the pool.
SURGE_TAINT_KEY = labels_mod.SURGE_TAINT_KEY
SURGE_TAINT = {
    "key": SURGE_TAINT_KEY, "value": "true", "effect": "NoSchedule",
}

#: The canonical set of named orchestrator crash points. ``_crash_point``
#: refuses names outside it, the kill-at-every-crash-point suites assert
#: they exhausted exactly this set, and the cclint crash-point coverage
#: checker fails the build when a member has no test naming it — so
#: adding a point here without extending the suites cannot land.
CRASH_POINTS = (
    "planned",
    "window-start",
    "mid-window",
    "awaited",
    "window-boundary",
    # Fired the moment the SLO gate pauses a wave: a kill here models the
    # orchestrator dying while latency-paused, and --resume must re-arm
    # the gate from the record (tests/test_rollout_resume.py).
    "slo-paused",
    # Fired after the surge spares' pre-staging completed (journaled) but
    # BEFORE their flip window opens: a kill here models the orchestrator
    # dying between prestage and flip — the successor never re-surges,
    # and the spares' held state converges them instantly when their
    # groups are re-driven as ordinary windows.
    "spare-prestaged",
    # Fired immediately before a federated shard's parent-record sync
    # (ccmanager/federation.py): a kill here models a regional
    # orchestrator dying between its own checkpoint and the cross-region
    # budget propagation — the successor's --resume re-attaches to the
    # parent and the set-union spend merge keeps the charge exactly-once.
    "federation-boundary",
    # Fired at the boundary sync where the shard FIRST recognizes the
    # parent plane as offline (grace elapsed, degraded mode entered): a
    # kill here models a regional orchestrator dying mid-blackout — the
    # successor's --resume must re-enter degraded mode from the
    # checkpointed escrow ledger without any parent round trip
    # (federation.py FederationGate.from_record_dict dark path).
    "parent-offline",
    # Fired after a continuous-prestage ledger reservation is durably
    # checkpointed (record v7) but BEFORE the node is armed: a kill here
    # leaves a charged-but-unarmed entry — the successor adopts it
    # (reserve() refuses a second charge) and re-arms it in place.
    "prestage-reserved",
    # Fired after the PRESTAGE annotation landed and the ledger entry
    # was marked armed + checkpointed: a kill here models the dual-wave
    # hazard — wave N+1 is mid-prestage while wave N drains — and the
    # successor must adopt the armed node AS-IS (no re-surge, no second
    # ledger charge), mirroring the spare rule at the surge resume.
    "prestage-armed",
    # Fired the moment a prestaged entry is found stale at its flip
    # window (plan digest mismatch, agent never held, or the hold
    # expired): a kill here leaves the entry charged — the successor
    # re-validates and releases it exactly once, and the node re-flips
    # via the full path (never converges against an old plan).
    "prestage-invalidate",
    # Fired after a fail-slow verdict is journaled in the record
    # (durably checkpointed) but BEFORE the containment action runs: a
    # kill here models the orchestrator dying mid-vetting — the
    # successor resumes the verdict FROM the record (entries marked
    # acted are skipped, unacted ones re-acted through the idempotent
    # ladder), so one confirmed verdict can never quarantine twice.
    # Fires only at boundaries where a new or unacted verdict exists.
    "failslow-vetted",
)


@dataclasses.dataclass
class SloGateConfig:
    """Parameters of the wave-boundary SLO gate (persisted in the
    RolloutRecord — rollout_state.py v4 — so crash + ``--resume`` stays
    latency-gated). The gate CALLABLE itself is injected separately
    (``slo_gate``): in-process it is ``SloEvaluator.breached`` over the
    live serve metrics (ServeHarness); ``tpu-cc-ctl rollout`` builds one
    that polls a serving pool's ``/metrics`` (``source``)."""

    #: Halt signal threshold: error-budget burn above this pauses the
    #: next wave (1.0 = spending exactly as provisioned).
    max_burn_rate: float = 1.0
    #: Optional absolute p99 target (ms); breached when exceeded.
    p99_target_ms: float | None = None
    #: SLO window the gate judges (None = the evaluator's fastest).
    window_s: float | None = None
    #: Pause budget: burn sustained past this halts the rollout like the
    #: failure budget does (a pool that cannot recover its SLO should
    #: stop being reconfigured, not wait forever half-flipped).
    max_pause_s: float = 300.0
    #: Metrics URL a remote gate polls (ctl); None for in-process gates.
    source: str | None = None

    def to_dict(self) -> dict:
        return {
            "max_burn_rate": self.max_burn_rate,
            "p99_target_ms": self.p99_target_ms,
            "window_s": self.window_s,
            "max_pause_s": self.max_pause_s,
            "source": self.source,
        }

    @classmethod
    def from_dict(cls, obj: dict) -> "SloGateConfig":
        # `is not None` throughout: 0.0 is a meaningful persisted value
        # for max_burn_rate (pause on ANY burn) and max_pause_s (halt
        # immediately on breach) — a falsy fallback would silently
        # weaken the gate on resume, the exact drop the v4 record
        # format exists to prevent.
        return cls(
            max_burn_rate=(
                float(obj["max_burn_rate"])
                if obj.get("max_burn_rate") is not None else 1.0
            ),
            p99_target_ms=(
                float(obj["p99_target_ms"])
                if obj.get("p99_target_ms") is not None else None
            ),
            window_s=(
                float(obj["window_s"])
                if obj.get("window_s") is not None else None
            ),
            max_pause_s=(
                float(obj["max_pause_s"])
                if obj.get("max_pause_s") is not None else 300.0
            ),
            source=obj.get("source") or None,
        )


def metrics_gate(config: SloGateConfig, fetch=None):
    """Build a gate callable that scrapes ``config.source`` (a serving
    pool's ``/metrics``) and judges it with
    :func:`~tpu_cc_manager.obs.slo.breached_from_metrics_text` — the
    remote form ``tpu-cc-ctl rollout --slo-source`` uses. A failed
    scrape reads NOT breached (fail-open, logged): missing telemetry
    must pause nobody — the gate protects users from the rollout, not
    the rollout from a dead scrape endpoint."""
    from tpu_cc_manager.obs import slo as slo_mod

    if fetch is None:
        def fetch(url: str) -> str:  # pragma: no cover - trivial I/O
            import urllib.request

            with urllib.request.urlopen(url, timeout=5) as resp:
                return resp.read().decode("utf-8", "replace")

    warned_window = [False]

    def gate() -> bool:
        try:
            text = fetch(config.source)
        except Exception as e:  # noqa: BLE001 - fail-open by design
            log.warning(
                "SLO gate scrape of %s failed (%s); reading NOT breached",
                config.source, e,
            )
            return False
        windows = slo_mod.parse_serve_slo_text(text)
        if not windows:
            return False  # no SLO gauges exported: no evidence
        if config.window_s is not None:
            stats = windows.get(float(config.window_s))
            if stats is None:
                if not warned_window[0]:
                    # A typo'd --slo-window would otherwise disable the
                    # gate SILENTLY for the whole rollout (no matching
                    # gauge = no evidence = not breached, forever).
                    warned_window[0] = True
                    log.warning(
                        "SLO gate window %ss is not among the windows "
                        "%s exports (%s); the gate reads NOT breached "
                        "until that window appears — check --slo-window",
                        config.window_s, config.source, sorted(windows),
                    )
                return False
        else:
            stats = windows[min(windows)]
        return slo_mod.breach_verdict(
            stats.get("burn_rate", 0.0), stats.get("p99_s"),
            config.max_burn_rate,
            (
                config.p99_target_ms / 1e3
                if config.p99_target_ms is not None else None
            ),
        )

    return gate


def headroom_gate_from_source(
    source: str, knee_rps: float, n_nodes: int, fetch=None
):
    """Build a continuous-prestage headroom gate that scrapes a serving
    pool's ``/metrics`` for the ``tpu_cc_serve_offered_rps`` gauge and
    converts the slack under ``knee_rps`` into whole nodes
    (:func:`~tpu_cc_manager.serve.sweep.knee_slack_nodes`) — the remote
    form ``tpu-cc-ctl rollout --prestage-knee-rps`` uses. Deliberately
    the mirror image of :func:`metrics_gate`: a failed scrape RAISES so
    ``_prestage_allowance`` reads zero slack (fail-closed) — prestage
    must never consume headroom it cannot prove exists, while the wave
    itself keeps rolling."""
    from tpu_cc_manager.obs import slo as slo_mod
    from tpu_cc_manager.serve import sweep as sweep_mod

    if fetch is None:
        def fetch(url: str) -> str:  # pragma: no cover - trivial I/O
            import urllib.request

            with urllib.request.urlopen(url, timeout=5) as resp:
                return resp.read().decode("utf-8", "replace")

    def gate() -> int:
        text = fetch(source)
        offered = slo_mod.parse_serve_offered_rps(text)
        if offered is None:
            return 0  # no gauge exported: no evidence of slack
        return sweep_mod.knee_slack_nodes(knee_rps, offered, n_nodes)

    return gate


#: Terminal await-state for a node whose Node OBJECT vanished mid-window
#: (cluster-autoscaler scale-down, spot reclaim). The informer delivers
#: the DELETED event (or the fallback GET answers 404), and the await
#: loop resolves the slot immediately instead of charging the node the
#: full window deadline as a timeout-in-progress. A deleted node is not
#: a CC failure: it never counts against the group's ok verdict or the
#: pool failure budget.
STATE_NODE_DELETED = "deleted"

#: Await-map state for a node abandoned at the peer-relative straggler
#: wall: its peers in this rollout converged, this node is still
#: converging beyond ``straggler_factor`` times the peer median, so the
#: await returns WITHOUT it instead of stretching the window to the full
#: node_timeout_s. Charged to the failure budget like a failure (the
#: node did not reach the mode), distinct in the states map so the
#: timeline and the record tell a straggler from a hard failure.
STATE_STRAGGLER = "straggler"


def partition_waves(
    groups: list[tuple[str, tuple[str, ...]]],
    labels_by_name: dict[str, dict],
    shards: int,
) -> list[list[tuple[str, tuple[str, ...]]]]:
    """Deterministically partition the group plan into up to ``shards``
    concurrent waves, keeping each zone's groups in ONE wave (a zone's
    groups stay strictly rolling relative to each other — concurrency
    comes from independent failure domains, not from flooding one zone).
    Groups without a zone label partition by their own id. Pure function
    of (plan, labels, shards), which is why the record never needs to
    store the partition (v1 records resume sharded for free). Note a
    resume partitions the SURVIVING todo groups, so a zone may land in a
    different wave than it did pre-crash — harmless, because the
    invariants live elsewhere: zone affinity is re-derived per call, and
    the budget/lease/record are shared across all waves. Only wave
    *membership* of a zone is resume-stable, not wave numbering."""
    keys: dict[str, str] = {}
    for gid, names in groups:
        zone = (labels_by_name.get(names[0]) or {}).get(ZONE_LABEL)
        keys[gid] = f"zone/{zone}" if zone else f"group/{gid}"
    assignment = {
        key: i % max(1, shards)
        for i, key in enumerate(sorted(set(keys.values())))
    }
    waves: list[list[tuple[str, tuple[str, ...]]]] = [
        [] for _ in range(max(1, shards))
    ]
    for gid, names in groups:
        waves[assignment[keys[gid]]].append((gid, names))
    return [w for w in waves if w]


def plan_groups(
    api: KubeApi, selector: str, nodes: list[dict] | None = None
) -> list[tuple[str, tuple[str, ...]]]:
    """Group matching nodes by slice id; single-host nodes group alone.

    Groups are ordered by name for deterministic rollouts. ``nodes`` lets a
    caller that already holds the listing avoid a second round trip.
    """
    if nodes is None:
        nodes = api.list_nodes(selector)
    groups: dict[str, list[str]] = {}
    for node in nodes:
        name = node["metadata"]["name"]
        slice_id = node_labels(node).get(SLICE_ID_LABEL) or f"node/{name}"
        groups.setdefault(slice_id, []).append(name)
    return [(gid, tuple(sorted(names))) for gid, names in sorted(groups.items())]


class RollingReconfigurator:
    # How many poll intervals a first-poll 'failed' state is presumed stale
    # (awaiting the agent's retry) before it is believed. Long enough for a
    # live agent to begin its apply (state leaves 'failed' on the first
    # reconcile), short enough that a dead agent fails the group in a few
    # polls instead of the full node timeout.
    STALE_FAILED_GRACE_POLLS = 5

    def __init__(
        self,
        api: KubeApi,
        selector: str,
        max_unavailable: int = 1,
        node_timeout_s: float = 600.0,
        poll_interval_s: float = 2.0,
        continue_on_failure: bool = False,
        rollback_on_failure: bool = False,
        failure_budget: int | None = None,
        lease: "rollout_state.RolloutLease | None" = None,
        resume_record: "rollout_state.RolloutRecord | None" = None,
        crash_hook=None,
        metrics: metrics_mod.MetricsRegistry | None = None,
        informer=None,
        wave_shards: int = 1,
        surge: int = 0,
        prestage: bool = False,
        prestage_timeout_s: float | None = None,
        continuous_prestage: bool = False,
        headroom_gate=None,
        adopt_new_nodes: bool = True,
        flight: "flight_mod.FlightRecorder | None" = None,
        slo_gate=None,
        slo_config: "SloGateConfig | None" = None,
        federation=None,
        failslow_vetter=None,
        failslow_act=None,
        straggler_factor: float | None = None,
        straggler_min_peers: int = 3,
        straggler_floor_s: float = 1.0,
    ) -> None:
        # Crash safety: with a lease, every write goes through the fence
        # (a lost lease refuses further patches) and progress is
        # checkpointed into the lease at every window boundary so a
        # successor can resume from ``resume_record``.
        self.lease = lease
        if lease is not None:
            api = rollout_state.FencedKube(api, lease, metrics=metrics)
        self.resume_record = resume_record
        self.generation = lease.generation if lease is not None else None
        # Test/chaos hook fired at named orchestrator crash points
        # ("planned", "window-start", "mid-window", "awaited",
        # "window-boundary") — FaultPlan.decide_orchestrator_kill raises
        # OrchestratorKilled here to model a SIGKILL landing at exactly
        # that point.
        self.crash_hook = crash_hook
        self.metrics = metrics if metrics is not None else metrics_mod.REGISTRY
        self.api = api
        self.selector = selector
        self.max_unavailable = max(1, max_unavailable)
        self.node_timeout_s = node_timeout_s
        self.poll_interval_s = poll_interval_s
        self.continue_on_failure = continue_on_failure
        self.rollback_on_failure = rollback_on_failure
        # Pool-level circuit breaker: when MORE than this many nodes of the
        # pool are quarantined, the rollout refuses to proceed — a fleet
        # bleeding nodes should stop being reconfigured, not have its
        # remaining capacity bounced. None = no budget.
        self.failure_budget = failure_budget
        # Transient apiserver failures during the per-poll listing ride the
        # shared jittered backoff instead of crashing the whole rollout —
        # one attempt when the client retries internally (RestKube), so
        # exactly one ladder runs per logical call.
        self.retry_policy = retry_mod.RetryPolicy(
            max_attempts=caller_retry_attempts(api),
            base_delay_s=min(1.0, max(0.01, poll_interval_s)),
            max_delay_s=max(1.0, poll_interval_s * 4),
        )
        # Checkpoints get their OWN attempts regardless of the client's
        # internal retries: the lease PUT is deliberately never retried
        # inside RestKube (a blind PUT retry would 409 its own write),
        # so caller_retry_attempts' collapse-to-1 would leave a single
        # connection reset aborting the whole rollout. Retrying
        # checkpoint() itself is safe — its 409 path re-reads and
        # disambiguates by holder identity.
        self.checkpoint_policy = retry_mod.RetryPolicy(
            max_attempts=3,
            base_delay_s=min(1.0, max(0.01, poll_interval_s)),
            max_delay_s=max(1.0, poll_interval_s * 4),
        )
        if continue_on_failure and rollback_on_failure:
            # Contradictory: one says press on past failures, the other
            # says undo on failure. Reject rather than silently pick one.
            raise ValueError(
                "continue_on_failure and rollback_on_failure are mutually "
                "exclusive"
            )
        # Informer-backed orchestration (ccmanager/informer.py): when set,
        # every pool read — planning, await polls, budget re-checks —
        # comes from the watch-driven cache, and awaits wake on cache
        # events instead of sleeping a poll interval. The informer must be
        # scoped to THE SAME selector (its cache IS the pool view).
        self.informer = informer
        if informer is not None and getattr(informer, "selector", selector) != selector:
            raise ValueError(
                f"informer watches {informer.selector!r} but the rollout "
                f"targets {selector!r}; they must agree"
            )
        # Sharded rollout waves: up to N concurrent lease-fenced
        # sub-rollouts partitioned by zone (fallback: by group), each
        # running its own strictly-rolling window loop of max_unavailable
        # groups, all under ONE failure budget, ONE lease and ONE record.
        self.wave_shards = max(1, int(wave_shards))
        if self.wave_shards > 1 and rollback_on_failure:
            # A rollback racing other shards' forward progress would
            # interleave revert and apply writes on the same pool; the
            # sharded path keeps the record honest instead (failed groups
            # stay failed; --resume re-drives them).
            raise ValueError(
                "rollback_on_failure is not supported with wave_shards > 1"
            )
        # Surge rollouts: flip up to this many SPARE nodes first, behind
        # the surge NoSchedule taint (unschedulable-for-workloads for the
        # flip window), then reclaim them — so the regular rolling waves
        # migrate workloads onto already-flipped capacity and measured
        # pool unavailability stays bounded by max_unavailable.
        self.surge = max(0, int(surge))
        # Zero-bounce spares (ROADMAP item 5): with ``prestage`` on, the
        # surge phase first ARMS its spares — surge taint + the PRESTAGE
        # annotation — and awaits the agents' pre-staged records (each
        # agent runs the full journaled flip + compile warmup and HOLDS,
        # manager.py) before opening the flip window, which then
        # converges in ~drain+readmit time. Spares pre-armed AHEAD of
        # the rollout (prestage_spares() / `ctl rollout --prestage-only`
        # — overlapping the pre-staging with live serving or a
        # preceding rollout wave) are detected either way and flip
        # instantly without any in-rollout arming wait. Agents that
        # never pre-stage (older binaries, CC_PRESTAGE=0) simply time
        # the await out and fall back to the full flip — prestaging is
        # an optimization, never a correctness gate.
        self.prestage = bool(prestage)
        self.prestage_timeout_s = (
            prestage_timeout_s if prestage_timeout_s is not None
            else node_timeout_s
        )
        # Whole-fleet zero-bounce (ROADMAP item 2): with
        # ``continuous_prestage`` on, the single-shard window loop
        # prestages the REGULAR nodes of upcoming windows (wave N+1)
        # while window N flips, under a crash-journaled capacity ledger
        # (rollout_state.CapacityLedger, record v7). Every prestage
        # CAS-reserves one node of transition headroom, bounded by
        # ``headroom_gate`` — a zero-arg callable returning how many
        # nodes of slack the offered load leaves under the serving knee
        # (serve.sweep.knee_slack_nodes). No gate = max_unavailable;
        # a gate that RAISES reads as zero slack (fail-CLOSED — the
        # opposite of the SLO gate, because prestage is an optimization
        # and must never consume headroom it cannot prove exists).
        # Prestage transitions are additionally capped at
        # max_unavailable so concurrent prestages can never violate the
        # rollout's own disruption bound. Sharded waves
        # (wave_shards > 1) roll without continuous prestage: the
        # ledger is a single-writer structure and the sharded suite
        # asserts no cross-shard coupling.
        self.continuous_prestage = bool(continuous_prestage)
        self.headroom_gate = headroom_gate
        # The live ledger: aliased to record.ledger when a record exists
        # (so every checkpoint persists it), or an in-memory ledger for
        # lease-less embedded callers (ServeHarness) — same invariants,
        # no crash durability to need them.
        self._ledger: "rollout_state.CapacityLedger | None" = None
        if self.surge > 0 and rollback_on_failure:
            # A surge halt would have to revert tainted spares (and the
            # halt path would silently skip the rollback otherwise) —
            # refuse the combination, like wave_shards does.
            raise ValueError(
                "rollback_on_failure is not supported with surge > 0"
            )
        # Autoscaler interplay: nodes created mid-rollout that match the
        # selector are adopted into a trailing wave (and stamped with the
        # rollout generation) instead of being silently left at the old
        # mode. Disable for byte-identical legacy behavior.
        self.adopt_new_nodes = adopt_new_nodes
        # Measured unavailability: how many non-surge groups are
        # concurrently mid-flip, across every wave thread. The max is the
        # rollout's observed disruption ceiling (RolloutResult
        # .max_unavailable_observed).
        self._inflight_lock = locks_mod.make_lock("rolling.inflight")
        self._inflight_groups = 0
        self._max_inflight_observed = 0
        # Serializes record mutation + checkpoint serialization across
        # wave threads (the lease's own write lock only covers the CAS).
        self._record_lock = locks_mod.make_rlock("rolling.record")
        # FaultPlan rngs are not thread-safe; crash points from concurrent
        # waves serialize so kill schedules stay a pure function of the
        # seed and the (serialized) decision sequence.
        self._crash_lock = locks_mod.make_lock("rolling.crash")
        # Flight recorder (obs/flight.py): every decision below lands as
        # one appended+flushed JSONL event, stamped with the rollout
        # generation and trace id. None = no timeline (tests, embedded
        # callers). A resumed rollout appends to the SAME file, so one
        # timeline spans the crash.
        self.flight = flight
        if flight is not None and self.generation is not None:
            flight.set_generation(self.generation)
        # SLO-paced rollouts (ROADMAP item 1): ``slo_gate`` is a zero-arg
        # callable returning True while the serving SLO is breached
        # (SloEvaluator.breached over the live serve metrics, or the
        # remote metrics_gate). Polled at EVERY wave boundary in both
        # the single-shard and sharded window loops: burn above budget
        # pauses the next wave (bounded, stop-aware), recovery resumes
        # it, burn sustained past the pause budget halts like the
        # failure budget. The config (not the callable) is persisted in
        # the record so crash + --resume stays latency-gated.
        self.slo_gate = slo_gate
        # A gate without an explicit config gets defaults — but remember
        # the config was synthesized: on resume the record's PERSISTED
        # gate parameters win over synthesized defaults (a library
        # caller re-arming with just the callable must not clobber the
        # pause budget / thresholds the record carries).
        self._slo_config_defaulted = slo_gate is not None and slo_config is None
        if self._slo_config_defaulted:
            slo_config = SloGateConfig()
        self.slo_config = slo_config
        # Federated region-sharded rollouts (ccmanager/federation.py):
        # when this orchestrator is one regional shard of a federation,
        # ``federation`` is its attached FederationGate. At every wave
        # boundary the shard syncs with the parent record (inside the
        # "federation-boundary" crash point): its regional budget spend
        # is union-merged up, the GLOBAL spend is folded back into the
        # regional record so the existing failure-budget math enforces
        # the single global budget, and a fenced shard (regional lease
        # lost, parent generation advanced, parent aborted) raises
        # RolloutFenced instead of writing another byte.
        self.federation = federation
        # Fail-slow containment (obs/failslow.py): ``failslow_vetter``
        # is polled at every window boundary — its ``concluded()``
        # verdicts are JOURNALED in the record (v8) and checkpointed
        # behind the "failslow-vetted" crash point BEFORE
        # ``failslow_act(node, entry)`` (typically
        # RemediationLadder.note_failslow via the harness) runs, so a
        # SIGKILL mid-containment resumes to the same single
        # quarantine. Its ``suspects()`` feed the continuous-prestage
        # headroom exclusion, and window groups whose every member is
        # CONFIRMED fail-slow are skipped like quarantined ones. Both
        # default to None: no vetter, no behavior change (the crash
        # point never fires without a journaled verdict).
        self.failslow_vetter = failslow_vetter
        self.failslow_act = failslow_act
        # Straggler-proof waves: when ``straggler_factor`` is set, an
        # await whose remaining nodes have been converging longer than
        # ``max(straggler_floor_s, factor * median(peer convergence))``
        # abandons them as STATE_STRAGGLER (budget-charged, window
        # wall released) instead of stretching to node_timeout_s. The
        # peer stats are this rollout's own cross-window convergence
        # history; below ``straggler_min_peers`` samples there is no
        # peer evidence and the wall stays node_timeout_s.
        self.straggler_factor = (
            float(straggler_factor) if straggler_factor else None
        )
        if self.straggler_factor is not None and self.straggler_factor <= 1.0:
            raise ValueError("straggler_factor must be > 1.0")
        self.straggler_min_peers = max(1, int(straggler_min_peers))
        self.straggler_floor_s = max(0.0, float(straggler_floor_s))
        # Per-node convergence walls this rollout observed (bounded;
        # guarded by _inflight_lock — awaits append from wave threads).
        self._converge_history: list[float] = []
        # Nodes with a journaled CONFIRMED verdict (acted or about to
        # be) / currently-suspect nodes, refreshed at each vet pass.
        self._failslow_confirmed: set[str] = set()
        self._failslow_suspects: set[str] = set()
        # Lease-less fallback journal (embedded callers without a
        # record): same shape as record.failslow, no crash durability.
        self._failslow_journal: dict[str, dict] = {}

    def _fl(self, event: str, **fields) -> None:
        """One flight-recorder event (no-op without a recorder)."""
        if self.flight is not None:
            self.flight.record(event, **fields)

    def _fl_group(
        self, gres: GroupResult, mode: str,
        wave: int | str | None, window: int | str | None,
        skipped: bool = False,
    ) -> None:
        """Terminal flight events for one awaited group: converged /
        failed / retired-deleted per node. ``skipped=True`` marks the
        idempotency-skip path (the node was VERIFIED at target, not
        driven) — the timeline reconstruction merges a skipped terminal
        with a real one instead of flagging a double-bounce."""
        if self.flight is None:
            return
        for name, state in gres.states.items():
            if state == STATE_NODE_DELETED:
                event = flight_mod.EVENT_NODE_RETIRED
            elif state == mode:
                event = flight_mod.EVENT_NODE_CONVERGED
            else:
                event = flight_mod.EVENT_NODE_FAILED
            self.flight.record(
                event, node=name, group=gres.group, state=state,
                wave=wave, window=window,
                skipped=skipped or None,
                seconds=round(gres.seconds, 3),
            )

    def rollout(self, mode: str) -> RolloutResult:
        mode = canonical_mode(mode)
        if mode not in VALID_MODES:
            # Fail fast: a typo'd mode written pool-wide would drive every
            # node agent to 'failed' (reason=invalid-mode) and the rollout
            # would still burn a full await per group before reporting.
            raise ValueError(
                f"invalid CC mode {mode!r} (valid: {VALID_MODES})"
            )
        # One rollout = one trace — and, unlike the pre-stitching era,
        # NOT a disjoint one: every desired-mode patch below carries
        # this trace's identity (labels.ROLLOUT_TRACE_LABEL), each node
        # agent adopts it as the remote parent of its reconcile root
        # span, and /tracez?trace_id=<this id> renders one causal tree
        # from this span down through every node's drain/reset/smoke.
        with obs_trace.root_span(
            "rollout", mode=mode, selector=self.selector,
            max_unavailable=self.max_unavailable,
        ) as sp:
            if self.flight is not None:
                self.flight.set_trace(sp.trace_id)
            result = self._rollout(mode)
            sp.set_attribute("ok", result.ok)
            sp.set_attribute("groups", len(result.groups))
            if not result.ok:
                sp.status = obs_trace.STATUS_ERROR
            self._fl(
                flight_mod.EVENT_COMPLETE, ok=result.ok,
                halted=result.halted_reason,
                groups=len(result.groups),
                retired_deleted=result.retired_deleted or None,
                adopted=result.adopted or None,
                surged=result.surged or None,
            )
            return result

    def _quarantined_of(self, listing: list[dict]) -> list[str]:
        from tpu_cc_manager.ccmanager.remediation import quarantined_nodes

        return quarantined_nodes(listing)

    def _budget_exceeded(self, spend: list[str]) -> bool:
        if self.failure_budget is None or len(spend) <= self.failure_budget:
            return False
        log.error(
            "pool failure budget exceeded: %d node(s) charged "
            "(quarantined or failed: %s), budget %d — halting rollout "
            "(fleet-level circuit breaker)",
            len(spend), spend, self.failure_budget,
        )
        return True

    def _slo_breached(self) -> bool:
        """One gate poll. A gate that RAISES reads as not breached
        (fail-open, logged): the gate exists to protect users from the
        rollout, and a broken telemetry path must not wedge the pool
        half-flipped — the failure budget still guards real damage."""
        if self.slo_gate is None:
            return False
        try:
            return bool(self.slo_gate())
        except Exception as e:  # noqa: BLE001 - fail-open by design
            log.warning("SLO gate poll failed (%s); reading NOT breached", e)
            return False

    def _slo_gate_wait(
        self,
        wave: int | str | None,
        window: int | str | None,
        stop: threading.Event | None = None,
    ) -> bool:
        """Wave-boundary SLO pacing: when the gate reports the serving
        SLO breached, pause the next wave — a bounded, stop-aware
        poll-wait (shared retry-ladder shape) that resumes the moment
        the window recovers and gives up after the configured pause
        budget. Returns True to proceed, False when the rollout must
        halt (sustained burn) or another wave already halted (``stop``
        set mid-pause — the caller re-checks it and stays silent)."""
        if not self._slo_breached():
            return True
        cfg = self.slo_config or SloGateConfig()
        self.metrics.record_slo_pause()
        log.warning(
            "SLO gate breached at wave %s window %s boundary: pausing "
            "the next wave (max %.0fs) until the window recovers",
            wave, window, cfg.max_pause_s,
        )
        self._fl(
            flight_mod.EVENT_SLO_PAUSED, wave=wave, window=window,
            max_burn_rate=cfg.max_burn_rate,
            p99_target_ms=cfg.p99_target_ms,
            max_pause_s=cfg.max_pause_s,
        )
        self._crash_point("slo-paused")
        paused_at = time.monotonic()

        def recovered_or_stopped() -> bool:
            if stop is not None and stop.is_set():
                return True
            return not self._slo_breached()

        recovered = retry_mod.poll_until(
            recovered_or_stopped, cfg.max_pause_s, self.poll_interval_s
        )
        if stop is not None and stop.is_set():
            return False  # another wave halted; nothing to journal here
        paused_s = round(time.monotonic() - paused_at, 3)
        if recovered:
            log.warning(
                "SLO window recovered after %.1fs; resuming the wave",
                paused_s,
            )
            self._fl(
                flight_mod.EVENT_SLO_RESUMED, wave=wave, window=window,
                paused_s=paused_s,
            )
            return True
        log.error(
            "SLO burn sustained past the %.0fs pause budget; halting the "
            "rollout (same contract as the failure budget: a pool that "
            "cannot hold its SLO stops being reconfigured)",
            cfg.max_pause_s,
        )
        self._fl(
            flight_mod.EVENT_SLO_HALT, wave=wave, window=window,
            paused_s=paused_s, reason="slo-burn-exceeded",
        )
        return False

    def _crash_point(self, point: str) -> None:
        """Named orchestrator crash points for chaos testing: the hook
        (FaultPlan.decide_orchestrator_kill) may raise OrchestratorKilled
        here, modeling a SIGKILL that runs no cleanup."""
        if self.crash_hook is not None:
            assert point in CRASH_POINTS, (
                f"undeclared crash point {point!r}: add it to "
                "rolling.CRASH_POINTS and a kill-at test"
            )
            with self._crash_lock:
                self.crash_hook(point)

    def _failslow_journal_of(self, record) -> dict[str, dict]:
        """The fail-slow verdict journal: lease-backed (record.failslow,
        checkpointed with every other rollout mutation) when a record
        exists, else the in-memory fallback for lease-less rollouts."""
        if record is not None:
            return record.failslow
        return self._failslow_journal

    def _failslow_vet(self, record, window_id: int) -> None:
        """One fail-slow pass at a window boundary: poll the vetter's
        concluded verdicts, JOURNAL any new ones into the record, then
        act every unacted entry exactly once through ``failslow_act``.

        Exactly-once shape (same contract as hardware intents): the
        verdict is checkpointed BEFORE containment runs, the
        ``failslow-vetted`` crash point fires between the journal write
        and the act, and the act is only marked done (and re-
        checkpointed) after it returns. A kill anywhere in between
        leaves an unacted journal entry the successor re-drives; the
        remediation ladder underneath is idempotent, so a replayed act
        cannot double-quarantine. An act that raises stays unacted and
        is retried at the next boundary. The vetter itself is
        fail-open: a raising vetter skips the pass, never halts the
        rollout."""
        journal = self._failslow_journal_of(record)
        concluded: list[dict] = []
        if self.failslow_vetter is not None:
            try:
                concluded = self.failslow_vetter.concluded()
                self._failslow_suspects = set(self.failslow_vetter.suspects())
            except Exception:
                log.warning(
                    "fail-slow vetter raised; skipping this vetting pass",
                    exc_info=True,
                )
                concluded = []
        with self._record_lock:
            new_keys: list[str] = []
            for entry in concluded:
                key = str(entry.get("id"))
                if key in journal:
                    continue
                journal[key] = {
                    "node": str(entry.get("node")),
                    "verdict": str(entry.get("verdict")),
                    "deviation": entry.get("deviation"),
                    "acted": False,
                }
                new_keys.append(key)
            unacted = [
                k for k, e in sorted(
                    journal.items(),
                    key=lambda kv: (len(kv[0]), kv[0]),  # numeric id order
                )
                if not e.get("acted")
            ]
        if not new_keys and not unacted:
            return
        # Journal-then-act: the verdicts are durable before any
        # containment runs, so a SIGKILL at the crash point resumes
        # them from the record instead of losing or replaying them.
        self._checkpoint(record)
        for key in new_keys:
            e = journal[key]
            self._fl(
                flight_mod.EVENT_FAILSLOW_VERDICT, verdict_id=key,
                node=e["node"], verdict=e["verdict"],
                deviation=e["deviation"], window=window_id,
            )
        self._crash_point("failslow-vetted")
        acted_any = False
        for key in unacted:
            e = journal[key]
            node = e.get("node")
            confirmed = e.get("verdict") == "confirmed"
            if confirmed:
                with self._record_lock:
                    self._failslow_confirmed.add(node)
                    if record is not None:
                        record.charge_budget([node])
            else:
                with self._record_lock:
                    self._failslow_confirmed.discard(node)
            try:
                if self.failslow_act is not None:
                    # The journal key IS the verdict id: handing it to
                    # the act lets an idempotent consumer dedup a
                    # replayed act after a mid-act SIGKILL.
                    self.failslow_act(node, {**e, "id": key})
            except Exception:
                log.error(
                    "fail-slow containment for %s (verdict %s) failed; "
                    "left unacted for retry at the next window boundary",
                    node, key, exc_info=True,
                )
                continue
            if confirmed:
                self._fl(
                    flight_mod.EVENT_BUDGET_CHARGE, nodes=[node],
                    reason="fail-slow", window=window_id,
                )
            with self._record_lock:
                e["acted"] = True
            acted_any = True
        if acted_any:
            self._checkpoint(record)

    def _list_pool(self) -> list[dict]:
        """The current pool view: the informer cache when present (zero
        apiserver round trips), else one retried selector listing."""
        if self.informer is not None:
            return self.informer.list()
        return self.retry_policy.call(
            lambda: self.api.list_nodes(self.selector),
            op="rollout.list_nodes",
            classify=classify_kube_error,
        )

    def _checkpoint(self, record, status: str | None = None) -> None:
        """Persist plan + progress into the lease (one CAS write that also
        renews it). Transient apiserver failures ride the shared retry
        policy; a CAS loss raises RolloutFenced — a fenced-out
        orchestrator must stop, not keep flipping nodes it no longer
        owns."""
        if record is None or self.lease is None:
            return
        # The record lock brackets both the status write and the
        # serialization inside lease.checkpoint (record.to_json): a wave
        # thread mutating `done` mid-serialization would checkpoint a
        # torn record.
        with self._record_lock:
            if status is not None:
                record.status = status
            self.checkpoint_policy.call(
                lambda: self.lease.checkpoint(record),
                op="rollout.checkpoint",
                classify=classify_kube_error,
            )

    def _spend(self, record, *extra_sets) -> list[str]:
        """The failure-budget spend: persisted pre-crash charges plus any
        freshly observed quarantined/failed sets. Under a federation the
        record's spend already carries the GLOBAL union (folded back at
        every parent sync), so one budget governs every region."""
        spend: set[str] = set()
        if record is not None:
            spend |= set(record.budget_spend)
        for s in extra_sets:
            spend |= set(s)
        return sorted(spend)

    def _federation_sync(
        self,
        record,
        status: str = rollout_state.RECORD_IN_PROGRESS,
        halted_reason: str | None = None,
        wave=None,
        window=None,
        boundary: bool = True,
    ) -> str | None:
        """One wave-boundary exchange with the federated parent record
        (no-op for non-federated rollouts). Pushes this region's spend
        and progress up (CAS, union-merged — exactly-once under races),
        folds the global spend back into the regional record, and
        returns a halt reason when the parent says stop (another region
        blew the shared budget). ``RolloutFenced`` — regional lease
        lost, parent generation advanced, parent aborted — propagates:
        a fenced shard stops mid-sentence. ``boundary=False`` marks a
        terminal status push, which is NOT a crash point: the regional
        record is already checkpointed terminal, so a kill there has
        nothing left to resume (the parent just sees the region stale
        until an operator re-drives or aborts)."""
        if self.federation is None:
            return None
        if boundary:
            self._crash_point("federation-boundary")
        with self._record_lock:
            spend = list(record.budget_spend) if record is not None else []
            done = len(record.done) if record is not None else 0
            total = len(record.groups) if record is not None else 0
        view = self.federation.sync(
            spend, status=status, done=done, total=total,
            halted_reason=halted_reason, lease_generation=self.generation,
        )
        with self._record_lock:
            if record is not None and view["spend"]:
                record.charge_budget(view["spend"])
            if record is not None and record.federation is not None:
                # Keep the checkpointed escrow ledger current: a SIGKILL
                # after this boundary must resume with the balance/acked
                # spend AS OF this sync, not as of attach (dark resume
                # charges strictly against this snapshot).
                record.federation = self.federation.to_record_dict()
        self._fl(
            flight_mod.EVENT_FEDERATION_SYNC,
            region=self.federation.region, wave=wave, window=window,
            status=status, spend=len(view["spend"]),
            parent_status=view["parent_status"],
        )
        if view.get("offline_edge"):
            # First boundary past the offline grace: the shard is now
            # autonomous, charging against its escrow slice alone.
            self._fl(
                flight_mod.EVENT_PARENT_OFFLINE,
                region=self.federation.region, wave=wave, window=window,
                offline_seconds=round(view.get("offline_seconds") or 0.0, 3),
                escrow=view.get("escrow"),
            )
            log.warning(
                "region %s: parent plane offline past grace — degraded "
                "mode, escrow balance %s",
                self.federation.region, view.get("escrow"),
            )
            if boundary:
                self._crash_point("parent-offline")
        if view.get("reconnected"):
            self._fl(
                flight_mod.EVENT_PARENT_RECONNECT,
                region=self.federation.region, wave=wave, window=window,
                escrow=view.get("escrow"),
            )
            log.info(
                "region %s: parent plane reconnected — dark spend "
                "reconciled, escrow balance %s",
                self.federation.region, view.get("escrow"),
            )
        if view["halted"]:
            log.error(
                "region %s: federation halt (%s) — stopping this shard",
                self.federation.region, view["reason"],
            )
            return view["reason"] or "federation-halted"
        return None

    def _federation_push_status(
        self, record, status: str, reason: str | None = None
    ) -> None:
        """Publish this region's terminal status to the parent — HALTED
        makes sibling regions stop buying disruption at their next
        boundary; COMPLETE lets the parent flip complete once every
        region reports in. Best-effort: the shard's outcome is already
        decided, and a fence or apiserver error here must not mask the
        real result being returned."""
        if self.federation is None:
            return
        try:
            self._federation_sync(
                record, status=status, halted_reason=reason, boundary=False
            )
        except (rollout_state.RolloutFenced, KubeApiError) as e:
            log.warning(
                "federation status propagation failed (non-fatal): %s", e
            )

    def _rollout(self, mode: str) -> RolloutResult:
        if self.informer is not None and not self.informer.synced:
            # The cache must hold a full listing before any decision reads
            # it; an unsynced informer would plan over an empty pool.
            self.informer.start()
            if not self.informer.wait_for_sync(60.0):
                raise KubeApiError(
                    None, "informer cache never synced; refusing to plan "
                    "a rollout over a possibly-empty pool view"
                )
        listing = self._list_pool()
        # Quarantined nodes are out of the rollout entirely: their agents
        # defer reconciles, so awaiting them only burns the node timeout,
        # and bouncing a condemned node's slice-mates around it helps
        # nobody (the whole group is skipped only if ALL its hosts are
        # quarantined — a partially-quarantined multi-host slice cannot
        # converge and is surfaced by the group's await instead).
        quarantined = self._quarantined_of(listing)
        if quarantined:
            log.warning(
                "skipping quarantined node(s): %s", quarantined
            )
            self._fl(
                flight_mod.EVENT_QUARANTINE_SKIP, nodes=list(quarantined)
            )
            listing = [
                n for n in listing
                if n["metadata"]["name"] not in quarantined
            ]
        record = self.resume_record
        resumed = record is not None
        if resumed:
            # A successor picking up a dead orchestrator's checkpoint:
            # the PLAN comes from the record (no group bounced twice, no
            # group silently dropped), budget spend carries over, but
            # quarantine skips are recomputed fresh — remediation kept
            # running while the orchestrator was dead.
            self.metrics.record_rollout_resume()
            log.warning(
                "resuming rollout of mode %s (generation %s -> %s): "
                "%d/%d group(s) already recorded done",
                record.mode, record.generation, self.generation,
                len(record.done), len(record.groups),
            )
            self._fl(
                flight_mod.EVENT_RESUME, mode=record.mode,
                prior_generation=record.generation,
                done_groups=len(record.done),
                total_groups=len(record.groups),
            )
            # A HALTED record being resumed is live again: every mid-
            # flight checkpoint must say in-progress, or a crash of THIS
            # run would leave a record the next invocation's auto-resume
            # refuses (it only adopts in-progress records) — silently
            # dropping the persisted budget spend and done map.
            record.status = rollout_state.RECORD_IN_PROGRESS
            # Re-persist the live settings: a resume that adjusted the
            # budget/concurrency must hand THOSE to its own successor.
            record.max_unavailable = self.max_unavailable
            record.failure_budget = self.failure_budget
            record.wave_shards = self.wave_shards
            record.surge = self.surge
            # Re-persist the gate config when this run carries an
            # EXPLICIT one; a resume without one — or with only the gate
            # callable and synthesized default config — keeps (and
            # rehydrates from) the record's persisted parameters: the
            # record never silently sheds or weakens its latency gate.
            if record.slo_gate and (
                self.slo_config is None or self._slo_config_defaulted
            ):
                self.slo_config = SloGateConfig.from_dict(record.slo_gate)
            elif self.slo_config is not None:
                record.slo_gate = self.slo_config.to_dict()
            if record.slo_gate and self.slo_gate is None:
                # A latency-gated record resumed without a gate callable
                # must not proceed ungated at full speed: rebuild the
                # remote gate from the persisted source, or refuse —
                # the same contract the ctl path and the v4 version
                # refusal enforce.
                if self.slo_config is not None and self.slo_config.source:
                    log.warning(
                        "resume: re-arming the persisted SLO gate from "
                        "its metrics source %s", self.slo_config.source,
                    )
                    self.slo_gate = metrics_gate(self.slo_config)
                else:
                    raise ValueError(
                        "resuming a latency-gated rollout without a "
                        "gate: the persisted config has no pollable "
                        "source, so pass slo_gate= (or abort the record)"
                    )
            if record.federation and self.federation is None:
                # A federated regional slice resumed without a gate
                # would run unfenced against the parent: its budget
                # spend never reaches the siblings and a force-abort
                # never reaches it. Refuse loudly — the ctl path
                # rebuilds the gate from the record instead.
                raise ValueError(
                    "resuming a FEDERATED regional record without a "
                    "federation gate: rebuild it from the record "
                    "(FederationGate.from_record_dict) or abort"
                )
            if self.federation is not None:
                # Re-stamp with THIS run's parent attachment (fresh
                # parent generation token) so the slice a successor
                # resumes from fences against the live parent.
                record.federation = self.federation.to_record_dict()
            if record.failslow:
                # Rehydrate the confirmed set from the journal in id
                # order (a later cleared verdict lifts an earlier
                # confirmed one); unacted entries are re-driven by the
                # first _failslow_vet pass, not here.
                for _k, e in sorted(
                    record.failslow.items(),
                    key=lambda kv: (len(kv[0]), kv[0]),
                ):
                    if e.get("verdict") == "confirmed":
                        self._failslow_confirmed.add(e.get("node"))
                    else:
                        self._failslow_confirmed.discard(e.get("node"))
        elif self.lease is not None:
            record = rollout_state.RolloutRecord(
                mode=mode, selector=self.selector,
                generation=self.generation or 0, groups=[],
                max_unavailable=self.max_unavailable,
                failure_budget=self.failure_budget,
                wave_shards=self.wave_shards,
                surge=self.surge,
                slo_gate=(
                    self.slo_config.to_dict()
                    if self.slo_config is not None else None
                ),
                federation=(
                    self.federation.to_record_dict()
                    if self.federation is not None else None
                ),
            )
        if record is not None:
            record.charge_budget(quarantined)
        if self.federation is not None:
            # Fold the global spend in BEFORE the pre-plan budget check:
            # a sibling region that already blew the shared budget must
            # halt this region at zero bounces, not after its first
            # window. ``boundary=False``: nothing is planned yet, so a
            # kill here has nothing federation-specific to resume.
            fed_reason = self._federation_sync(
                record, window=-1, boundary=False,
            )
            if fed_reason is not None:
                if record is not None and record.groups:
                    self._checkpoint(
                        record, status=rollout_state.RECORD_HALTED,
                    )
                self._fl(
                    flight_mod.EVENT_HALT, reason=fed_reason, at="pre-plan",
                )
                return RolloutResult(
                    mode=mode, ok=False, groups=[],
                    skipped_quarantined=quarantined,
                    halted_reason=fed_reason,
                    resumed=resumed, generation=self.generation,
                )
        if self._budget_exceeded(self._spend(record, quarantined)):
            # Only checkpoint when the record carries a real plan (a
            # resumed record): a FRESH run halted before planning has
            # nothing to resume, and persisting its empty-groups record
            # would make a later --resume no-op with ok=true while no
            # node was ever reconfigured.
            if record is not None and record.groups:
                self._checkpoint(record, status=rollout_state.RECORD_HALTED)
            self._fl(
                flight_mod.EVENT_HALT, reason="failure-budget-exceeded",
                spend=self._spend(record, quarantined), at="pre-plan",
            )
            self._federation_push_status(
                record, rollout_state.RECORD_HALTED,
                reason="failure-budget-exceeded",
            )
            return RolloutResult(
                mode=mode, ok=False, groups=[],
                skipped_quarantined=quarantined,
                halted_reason="failure-budget-exceeded",
                resumed=resumed, generation=self.generation,
            )
        # Every node present at plan time: the adoption scan at the end
        # treats anything beyond this set (and not quarantined) as an
        # autoscaler scale-up to fold into a trailing wave.
        known_nodes = {n["metadata"]["name"] for n in listing} | set(
            quarantined
        )
        if resumed:
            groups = []
            for gid, names in record.groups:
                keep = tuple(n for n in names if n not in quarantined)
                if keep:
                    groups.append((gid, keep))
        else:
            groups = plan_groups(self.api, self.selector, nodes=listing)
            if record is not None:
                record.groups = list(groups)
        log.info(
            "rolling %s over %d group(s) (%d node(s)), max_unavailable=%d",
            mode, len(groups),
            sum(len(n) for _, n in groups), self.max_unavailable,
        )
        self._fl(
            flight_mod.EVENT_PLAN, mode=mode, groups=len(groups),
            nodes=sum(len(n) for _, n in groups),
            max_unavailable=self.max_unavailable,
            wave_shards=self.wave_shards, surge=self.surge or None,
            resumed=resumed or None,
        )
        results: list[GroupResult] = []
        window_seconds: list[float] = []
        # Idempotent resume (an interrupted rollout re-run must not re-bounce
        # what already converged): groups whose every node already carries
        # BOTH desired=mode and state=mode are recorded as skipped — no
        # label rewrite, no disruption, no await. A resumed record's done
        # groups are skipped on the record's say-so alone: their agents
        # already converged once, and re-awaiting them would re-burn the
        # node timeout if one has since drifted (drift is a new failure,
        # surfaced by the NEXT rollout, not silently folded into this one).
        labels_by_name = {
            n["metadata"]["name"]: node_labels(n) for n in listing
        }
        todo: list[tuple[str, tuple[str, ...]]] = []
        for gid, names in groups:
            done = record.done.get(gid) if resumed else None
            if done is not None and done.get("ok"):
                log.info(
                    "group %s already %s by the interrupted rollout; "
                    "skipping (no second bounce)",
                    gid, "skipped" if done.get("skipped") else "converged",
                )
                results.append(GroupResult(
                    group=gid, nodes=names, ok=True, seconds=0.0,
                    states={n: mode for n in names}, skipped=True,
                ))
                # The terminal per-node events were written before the
                # record checkpointed this group done (events precede
                # every checkpoint), so the timeline already has them:
                # only the skip decision itself is new information.
                self._fl(
                    flight_mod.EVENT_GROUP_SKIPPED, group=gid,
                    nodes=list(names), why="record-done",
                )
                continue
            if done is not None:
                # A group the dead orchestrator saw FAIL: re-drive it (the
                # operator re-ran the rollout on purpose), but its failed
                # nodes stay charged against the budget.
                record.done.pop(gid, None)
            if all(
                labels_by_name.get(n, {}).get(CC_MODE_LABEL) == mode
                and labels_by_name.get(n, {}).get(CC_MODE_STATE_LABEL) == mode
                for n in names
            ):
                log.info("group %s already at %s; skipping", gid, mode)
                gres = GroupResult(
                    group=gid, nodes=names, ok=True, seconds=0.0,
                    states={n: mode for n in names}, skipped=True,
                )
                results.append(gres)
                self._fl(
                    flight_mod.EVENT_GROUP_SKIPPED, group=gid,
                    nodes=list(names), why="already-at-target",
                )
                # skipped=True: these nodes were VERIFIED at target, not
                # driven — a successor re-observing a group whose
                # terminal events outran the dead orchestrator's last
                # checkpoint merges in the reconstruction instead of
                # reading as a double bounce.
                self._fl_group(gres, mode, wave=None, window=None,
                               skipped=True)
                if record is not None:
                    record.note_group(
                        gid, ok=True, states={n: mode for n in names},
                        seconds=0.0, skipped=True,
                    )
            else:
                todo.append((gid, names))
        groups = todo
        if self.continuous_prestage and self.wave_shards <= 1:
            # The capacity ledger rides the record (v7) when one exists
            # so every checkpoint persists it; lease-less callers get an
            # in-memory ledger with the same invariants.
            if record is not None:
                if record.ledger is None:
                    record.ledger = rollout_state.CapacityLedger()
                self._ledger = record.ledger
            else:
                self._ledger = rollout_state.CapacityLedger()
            if resumed and self._ledger.entries:
                self._prestage_adopt(mode, groups, record)
        elif self.continuous_prestage:
            log.warning(
                "continuous prestage is single-shard only (the ledger "
                "is a single-writer structure); wave_shards=%d rolls "
                "without it", self.wave_shards,
            )
        if (
            not (self.continuous_prestage and self.wave_shards <= 1)
            and record is not None
            and record.ledger is not None
            and record.ledger.entries
        ):
            # Degraded mode (--no-prestage on a ledgered record): every
            # checkpointed entry is released and its agent's hold
            # aborted — the ledger balances, every node takes the full
            # flip path, and the drained ledger is persisted with the
            # next checkpoint.
            log.warning(
                "prestage disabled on a ledgered record: releasing %d "
                "entr(ies); every node takes the full flip path",
                len(record.ledger.entries),
            )
            with self._record_lock:
                for name in list(record.ledger.entries):
                    self._prestage_clear_arm(name)
                    record.ledger.release(name)
                    self.metrics.record_prestage("aborted")
                    self._fl(
                        flight_mod.EVENT_PRESTAGE_RELEASED, node=name,
                        outcome="aborted", resumed=True,
                    )
        # Pre-rollout desired mode per node, for rollback_on_failure: read
        # from the pool listing already in hand — the rollout itself only
        # rewrites CC_MODE_LABEL on nodes it is about to await, so the
        # snapshot stays accurate for every later window, and the rollout
        # no longer spends O(pool) GET round trips before each window
        # (VERDICT r5 weak #7).
        prior: dict[str, str | None] = {}
        if self.rollback_on_failure:
            for _, names in groups:
                for name in names:
                    prior[name] = labels_by_name.get(name, {}).get(CC_MODE_LABEL)
        # First durable checkpoint: the full plan exists before any node is
        # touched, so even a kill INSIDE the first window leaves a
        # resumable record.
        self._checkpoint(record)
        self._crash_point("planned")
        # First parent exchange: publish this region's plan size and fold
        # the global spend in BEFORE any node is touched — a sibling that
        # already blew the shared budget halts this region at zero cost.
        fed_reason = self._federation_sync(record, window=-1)
        if fed_reason is not None:
            self._checkpoint(record, status=rollout_state.RECORD_HALTED)
            self._fl(
                flight_mod.EVENT_HALT, reason=fed_reason,
                at="federation-boundary",
            )
            return RolloutResult(
                mode=mode, ok=False, groups=results,
                window_seconds=window_seconds,
                skipped_quarantined=quarantined,
                halted_reason=fed_reason,
                resumed=resumed, generation=self.generation,
                retired_deleted=self._deleted_of(results),
                max_unavailable_observed=self._max_inflight_observed,
            )
        surged: list[str] = []
        surge_ok = True
        if self.surge > 0 and resumed:
            # A resume NEVER re-surges: the original spares are either
            # done (skipped above) or back in the plan as ordinary
            # groups, and greedily re-picking "spares" from what are now
            # serving nodes would flip up to `surge` of them concurrently
            # behind a NoSchedule taint that evicts nothing — silently
            # exceeding the max_unavailable guarantee. Surviving groups
            # roll at max_unavailable; stale surge taints a mid-surge
            # crash left behind are reclaimed here.
            stale = [
                node["metadata"]["name"]
                for node in listing
                if any(
                    t.get("key") == SURGE_TAINT_KEY
                    for t in (node.get("spec") or {}).get("taints") or []
                )
            ]
            if stale:
                log.warning(
                    "resume: reclaiming stale surge taint(s) from %s "
                    "(the interrupted surge phase is not re-run)", stale,
                )
                self._taint_surge(tuple(stale), add=False)
        elif self.surge > 0 and groups:
            surge_ok, groups, surged = self._surge_first(
                mode, groups, record, results, window_seconds
            )
            if not surge_ok and not self.continue_on_failure:
                log.error(
                    "surge group(s) failed; halting before the rolling "
                    "waves (%d group(s) not attempted)", len(groups),
                )
                self._fl(
                    flight_mod.EVENT_HALT, reason="surge-failed",
                    not_attempted=len(groups),
                )
                self._checkpoint(record, status=rollout_state.RECORD_HALTED)
                self._federation_push_status(
                    record, rollout_state.RECORD_HALTED,
                    reason="surge-failed",
                )
                return RolloutResult(
                    mode=mode, ok=False, groups=results,
                    window_seconds=window_seconds,
                    skipped_quarantined=quarantined,
                    resumed=resumed, generation=self.generation,
                    retired_deleted=self._deleted_of(results),
                    surged=surged,
                    max_unavailable_observed=self._max_inflight_observed,
                )
        if self.wave_shards > 1 and len(groups) > 1:
            return self._rollout_waves(
                mode, groups, labels_by_name, record, results,
                window_seconds, quarantined, resumed, surged, known_nodes,
                surge_ok,
            )
        # A failed spare under continue_on_failure presses on but must
        # still fail the rollout's verdict — a node sits failed (and
        # tainted) behind it.
        ok = surge_ok
        # Strictly bounded concurrency: process in windows of max_unavailable.
        for i in range(0, len(groups), self.max_unavailable):
            # Also re-checked at i=0 when a surge phase ran: its failures
            # are already charged, and a blown budget must not buy one
            # more window of real disruption.
            if (i or surged) and self.failure_budget is not None:
                # Re-check the budget at every window boundary: remediation
                # ladders run concurrently with the rollout, and a pool
                # that started bleeding nodes mid-rollout must stop being
                # reconfigured even though it started healthy. The spend
                # also carries every pre-crash charge from the record — a
                # node that failed before the orchestrator died still
                # counts, even if it has since been unquarantined.
                fresh = self._quarantined_of(self._list_pool())
                if record is not None:
                    record.charge_budget(fresh)
                if self._budget_exceeded(
                    self._spend(record, quarantined, fresh)
                ):
                    self._checkpoint(
                        record, status=rollout_state.RECORD_HALTED
                    )
                    self._fl(
                        flight_mod.EVENT_HALT,
                        reason="failure-budget-exceeded",
                        spend=self._spend(record, quarantined, fresh),
                        at="window-boundary",
                    )
                    self._federation_push_status(
                        record, rollout_state.RECORD_HALTED,
                        reason="failure-budget-exceeded",
                    )
                    return RolloutResult(
                        mode=mode, ok=False, groups=results,
                        window_seconds=window_seconds,
                        skipped_quarantined=sorted(set(quarantined) | set(fresh)),
                        halted_reason="failure-budget-exceeded",
                        resumed=resumed, generation=self.generation,
                        retired_deleted=self._deleted_of(results),
                        surged=surged,
                        max_unavailable_observed=self._max_inflight_observed,
                    )
            window = groups[i : i + self.max_unavailable]
            window_id = i // self.max_unavailable
            if i or surged:
                # Wave-boundary parent exchange: push this region's
                # spend/progress, fold the GLOBAL spend back (so the
                # budget re-check above sees sibling charges next
                # round), and honor a parent-declared halt.
                fed_reason = self._federation_sync(record, window=window_id)
                if fed_reason is not None:
                    self._checkpoint(
                        record, status=rollout_state.RECORD_HALTED
                    )
                    self._fl(
                        flight_mod.EVENT_HALT, reason=fed_reason,
                        at="federation-boundary", window=window_id,
                    )
                    return RolloutResult(
                        mode=mode, ok=False, groups=results,
                        window_seconds=window_seconds,
                        skipped_quarantined=quarantined,
                        halted_reason=fed_reason,
                        resumed=resumed, generation=self.generation,
                        retired_deleted=self._deleted_of(results),
                        surged=surged,
                        max_unavailable_observed=self._max_inflight_observed,
                    )
            # SLO pacing: the gate is polled at every wave boundary —
            # burn above budget pauses this window until the serving
            # window recovers; sustained burn halts like the failure
            # budget (the pool keeps serving; nothing else is bounced).
            if not self._slo_gate_wait(wave=0, window=window_id):
                self._checkpoint(record, status=rollout_state.RECORD_HALTED)
                self._federation_push_status(
                    record, rollout_state.RECORD_HALTED,
                    reason="slo-burn-exceeded",
                )
                return RolloutResult(
                    mode=mode, ok=False, groups=results,
                    window_seconds=window_seconds,
                    skipped_quarantined=quarantined,
                    halted_reason="slo-burn-exceeded",
                    resumed=resumed, generation=self.generation,
                    retired_deleted=self._deleted_of(results),
                    surged=surged,
                    max_unavailable_observed=self._max_inflight_observed,
                )
            # Fail-slow vetting at the window boundary: journal any new
            # peer-relative verdicts, then act them (restart -> quarantine
            # ladder) behind the failslow-vetted crash point. Runs before
            # the window timer so containment never counts against the
            # measured disruption wall.
            if self.failslow_vetter is not None or (
                record is not None and record.failslow
            ):
                self._failslow_vet(record, window_id)
            if self._failslow_confirmed:
                # A group whose EVERY member holds a confirmed fail-slow
                # verdict is already quarantined (or being quarantined) by
                # the ladder — flipping it would just burn the window wall
                # on a node we intend to drain. Partially-confirmed
                # multi-host groups still flip whole: slice atomicity wins
                # over skipping.
                kept = []
                for gid, names in window:
                    if names and all(
                        n in self._failslow_confirmed for n in names
                    ):
                        log.warning(
                            "skipping group %s: all members confirmed "
                            "fail-slow (%s)", gid, sorted(names),
                        )
                        self._fl(
                            flight_mod.EVENT_QUARANTINE_SKIP,
                            nodes=list(names), group=gid, why="fail-slow",
                        )
                        continue
                    kept.append((gid, names))
                window = kept
                if not window:
                    continue
            # Continuous prestage maintenance: runs BEFORE the window
            # timer starts, so prestage awaits never count against the
            # measured per-window disruption wall — the whole point is
            # that the flip window itself then closes in ~drain+readmit.
            if self._ledger is not None:
                self._prestage_maintain(mode, groups, i, record, window_id)
            self._crash_point("window-start")
            started = time.monotonic()
            self._note_window_inflight(len(window))
            self._fl(
                flight_mod.EVENT_WINDOW_OPEN, wave=0, window=window_id,
                groups=[gid for gid, _ in window],
            )
            for gid, names in window:
                self._set_desired(names, mode, wave=0, window=window_id)
            self._crash_point("mid-window")
            # Always await the FULL window even after a failure: every group
            # in it already received its desired label and is transitioning —
            # halting without awaiting would report in-flight slices as
            # untouched.
            window_failed = []
            for gid, names in window:
                gres = self._await_group(gid, names, mode, started)
                results.append(gres)
                self._fl_group(gres, mode, wave=0, window=window_id)
                if record is not None:
                    record.note_group(gid, gres.ok, gres.states, gres.seconds)
                    if not gres.ok:
                        # Deleted nodes are retired, not charged: the
                        # autoscaler reclaiming a VM is not a CC failure,
                        # and spending budget on it would let routine
                        # scale-downs halt a healthy rollout.
                        charged = [
                            n for n, s in gres.states.items()
                            if s not in (mode, STATE_NODE_DELETED)
                        ]
                        record.charge_budget(charged)
                        self._fl(
                            flight_mod.EVENT_BUDGET_CHARGE, nodes=charged,
                            group=gid, wave=0, window=window_id,
                        )
                if not gres.ok:
                    ok = False
                    window_failed.append(gid)
                # A held prestage that just converged (or failed) gives
                # its headroom back: released exactly once, under the
                # record lock, BEFORE the "awaited" checkpoint persists
                # the balanced ledger.
                if self._ledger is not None:
                    self._prestage_release_group(
                        names, outcome="converged" if gres.ok else "failed",
                        window=window_id,
                    )
            self._note_window_inflight(-len(window))
            window_seconds.append(time.monotonic() - started)
            self._fl(
                flight_mod.EVENT_WINDOW_CLOSE, wave=0, window=window_id,
                seconds=round(time.monotonic() - started, 3),
                failed=window_failed or None,
            )
            self._crash_point("awaited")
            self._checkpoint(record)
            self._crash_point("window-boundary")
            if window_failed and not self.continue_on_failure:
                log.error(
                    "group(s) %s failed; halting rollout (%d group(s) not "
                    "attempted)", window_failed, len(groups) - i - len(window),
                )
                self._fl(
                    flight_mod.EVENT_HALT, reason="group-failed",
                    failed=window_failed, wave=0, window=window_id,
                    not_attempted=len(groups) - i - len(window),
                )
                if self.rollback_on_failure and record is not None:
                    # A rolled-back group is NOT done: its desired label
                    # is about to be reverted to the pre-rollout mode.
                    # The done entries are popped and checkpointed BEFORE
                    # any revert write — a crash mid-rollback must not
                    # leave a durable record claiming reverted groups
                    # converged (a later --resume would skip them on the
                    # record's say-so and report a half-flipped pool
                    # green). Groups the interrupted rollback never got
                    # to are re-judged by the successor's fresh
                    # desired==state idempotency check, which skips them
                    # without a bounce.
                    for g in results:
                        if g.ok and not g.skipped:
                            record.done.pop(g.group, None)
                    self._checkpoint(record)
                rolled_back = (
                    self._rollback(results, prior)
                    if self.rollback_on_failure
                    else []
                )
                self._checkpoint(record, status=rollout_state.RECORD_HALTED)
                self._federation_push_status(
                    record, rollout_state.RECORD_HALTED,
                    reason="group-failed",
                )
                return RolloutResult(
                    mode=mode, ok=False, groups=results,
                    window_seconds=window_seconds, rolled_back=rolled_back,
                    skipped_quarantined=quarantined,
                    resumed=resumed, generation=self.generation,
                    retired_deleted=self._deleted_of(results),
                    surged=surged,
                    max_unavailable_observed=self._max_inflight_observed,
                )
        adopted: list[str] = []
        adopt_halted = None
        if (
            self.adopt_new_nodes
            and not self.rollback_on_failure
            and (ok or self.continue_on_failure)
        ):
            adopted, adopt_ok, adopt_halted = self._adopt_new_nodes(
                mode, record, results, window_seconds, known_nodes
            )
            ok = ok and adopt_ok
        if self._ledger is not None and self._ledger.entries:
            # Terminal drain: a COMPLETE record must carry a balanced
            # ledger (every charge released). Anything still entried
            # here was reserved for a group that never flipped (plan
            # shrank under us) — release it as aborted; the halt paths
            # above deliberately KEEP their entries for --resume to
            # adopt.
            with self._record_lock:
                for name in list(self._ledger.entries):
                    self._ledger.release(name)
                    self.metrics.record_prestage("aborted")
                    self._fl(
                        flight_mod.EVENT_PRESTAGE_RELEASED, node=name,
                        outcome="aborted",
                    )
                self.metrics.set_prestage_reserved(
                    self._ledger.in_transition()
                )
        self._checkpoint(
            record,
            status=(
                rollout_state.RECORD_COMPLETE if ok
                else rollout_state.RECORD_HALTED
            ),
        )
        self._federation_push_status(
            record,
            rollout_state.RECORD_COMPLETE if ok
            else rollout_state.RECORD_HALTED,
            reason=adopt_halted if not ok else None,
        )
        return RolloutResult(
            mode=mode, ok=ok, groups=results, window_seconds=window_seconds,
            skipped_quarantined=quarantined,
            halted_reason=adopt_halted,
            resumed=resumed, generation=self.generation,
            retired_deleted=self._deleted_of(results),
            adopted=adopted, surged=surged,
            max_unavailable_observed=self._max_inflight_observed,
        )

    # -- surge rollouts ---------------------------------------------------

    def _surge_first(
        self,
        mode: str,
        groups: list[tuple[str, tuple[str, ...]]],
        record,
        results: list[GroupResult],
        window_seconds: list[float],
    ) -> tuple[bool, list[tuple[str, tuple[str, ...]]], list[str]]:
        """Flip up to ``self.surge`` spare nodes FIRST, behind the surge
        NoSchedule taint: the spares are unschedulable-for-workloads for
        exactly their flip window, so their disruption never subtracts
        from the pool's serving capacity, and once reclaimed (taint
        removed) they absorb the workloads the regular rolling waves
        drain off the rest of the pool.

        Groups are picked greedily in plan order while they fit the
        remaining surge budget (a multi-host slice flips as one unit and
        is skipped rather than split). All picked spares flip
        concurrently — the taint, not ``max_unavailable``, bounds them —
        and deliberately do NOT count toward the measured pool
        unavailability (:meth:`_note_window_inflight`). Returns
        (every surge group converged, the remaining plan, surged node
        names)."""
        spares, rest = self._pick_spares(groups)
        if not spares:
            log.warning(
                "surge=%d requested but no group fits the spare budget "
                "(smallest group is larger); rolling normally", self.surge,
            )
            return True, list(groups), []
        surged = sorted(n for _, names in spares for n in names)
        log.info(
            "surge: flipping %d spare node(s) in %d group(s) first, "
            "behind the %s taint", len(surged), len(spares), SURGE_TAINT_KEY,
        )
        self._fl(
            flight_mod.EVENT_SURGE_PICK, nodes=surged,
            groups=[gid for gid, _ in spares],
        )
        if self.prestage:
            # Zero-bounce spares: arm + await pre-staging (or detect
            # spares pre-armed ahead of the rollout), journal each
            # pre-staged spare, then open the flip window — which for a
            # pre-staged spare converges in ~drain+readmit time via the
            # agent's idempotent re-attest path.
            prestaged = self._prestage_phase(mode, spares)
            if prestaged:
                for gid, names in spares:
                    for name in names:
                        rec = prestaged.get(name)
                        if rec is not None:
                            self._fl(
                                flight_mod.EVENT_SPARE_PRESTAGED,
                                node=name, group=gid,
                                seconds=rec.get("seconds"),
                            )
                self._crash_point("spare-prestaged")
        self._crash_point("window-start")
        started = time.monotonic()
        self._fl(
            flight_mod.EVENT_WINDOW_OPEN, wave="surge", window=0,
            groups=[gid for gid, _ in spares],
        )
        for _, names in spares:
            self._taint_surge(names, add=True)
            self._set_desired(names, mode, wave="surge", window=0)
        self._crash_point("mid-window")
        ok = True
        for gid, names in spares:
            gres = self._await_group(gid, names, mode, started)
            results.append(gres)
            self._fl_group(gres, mode, wave="surge", window=0)
            with self._record_lock:
                if record is not None:
                    record.note_group(gid, gres.ok, gres.states, gres.seconds)
                    if not gres.ok:
                        record.charge_budget(
                            n for n, s in gres.states.items()
                            if s not in (mode, STATE_NODE_DELETED)
                        )
            if gres.ok:
                # Reclaim: the converged spare rejoins the schedulable
                # pool immediately — capacity the regular waves migrate
                # workloads onto. A failed spare KEEPS its taint (a node
                # that could not flip must not receive workloads; the
                # operator untaints after diagnosing).
                self._taint_surge(names, add=False)
            else:
                ok = False
        window_seconds.append(time.monotonic() - started)
        self._fl(
            flight_mod.EVENT_WINDOW_CLOSE, wave="surge", window=0,
            seconds=round(time.monotonic() - started, 3),
            failed=None if ok else [g for g, _ in spares],
        )
        self._crash_point("awaited")
        self._checkpoint(record)
        self._crash_point("window-boundary")
        return ok, rest, surged

    def _taint_surge(self, names: tuple[str, ...], add: bool) -> None:
        """Apply/remove the surge NoSchedule taint. Retried like every
        other rollout write; a node whose object vanished (scale-down
        racing the surge) is skipped — the await retires it."""
        for name in names:
            try:
                self.retry_policy.call(
                    lambda name=name: (
                        self.api.patch_node_taints(
                            name, [dict(SURGE_TAINT)], []
                        )
                        if add
                        else self.api.patch_node_taints(
                            name, [], [SURGE_TAINT_KEY]
                        )
                    ),
                    op="rollout.surge_taint",
                    classify=classify_kube_error,
                )
            except KubeApiError as e:
                if e.status != 404:
                    raise
                log.warning(
                    "node %s vanished before its surge taint %s "
                    "(autoscaler scale-down); skipping",
                    name, "write" if add else "removal",
                )

    def _pick_spares(
        self, groups: list[tuple[str, tuple[str, ...]]]
    ) -> tuple[
        list[tuple[str, tuple[str, ...]]], list[tuple[str, tuple[str, ...]]]
    ]:
        """Greedy plan-order spare pick: groups that fit the remaining
        surge budget become spares (a multi-host slice flips as one unit
        and is skipped rather than split). Pure function of the plan, so
        a `--prestage-only` arm and the later surge rollout pick the
        SAME spares."""
        spares: list[tuple[str, tuple[str, ...]]] = []
        rest: list[tuple[str, tuple[str, ...]]] = []
        budget = self.surge
        for gid, names in groups:
            if 0 < len(names) <= budget:
                spares.append((gid, names))
                budget -= len(names)
            else:
                rest.append((gid, names))
        return spares, rest

    def _prestaged_record_of(self, node: dict, mode: str) -> dict | None:
        """The node's pre-staged status record, when it is VALID for this
        rollout: the PRESTAGED annotation parses, names ``mode``, and the
        node's state label confirms it still holds it (a record without
        the held state is stale — the agent reverted or never finished)."""
        from tpu_cc_manager.kubeclient.api import node_annotations

        raw = node_annotations(node).get(labels_mod.PRESTAGED_ANNOTATION)
        if not raw:
            return None
        try:
            obj = json.loads(raw)
        except ValueError:
            return None
        if not isinstance(obj, dict):
            return None
        if canonical_mode(str(obj.get("mode") or "")) != mode:
            return None
        if node_labels(node).get(CC_MODE_STATE_LABEL) != mode:
            return None
        return obj

    def _prestage_phase(
        self,
        mode: str,
        spares: list[tuple[str, tuple[str, ...]]],
    ) -> dict[str, dict]:
        """Arm (surge taint + PRESTAGE annotation) and await the spares'
        pre-staged records. Spares already holding a valid record (armed
        ahead of the rollout) are detected without any wait; agents that
        never pre-stage time the bounded await out and fall back to the
        full flip. Returns {node: prestaged-record} for every spare
        holding a valid record at the end of the phase."""
        names = [n for _, ns in spares for n in ns]
        by_name: dict[str, dict] = {}

        def scan() -> bool:
            nodes = {
                n["metadata"]["name"]: n for n in self._list_pool()
            }
            for name in names:
                node = nodes.get(name)
                if node is None:
                    continue
                rec = self._prestaged_record_of(node, mode)
                if rec is not None:
                    by_name[name] = rec
            return len(by_name) == len(names)

        if scan():
            log.info(
                "surge: all %d spare(s) already pre-staged for %s "
                "(armed ahead of the rollout)", len(names), mode,
            )
            return by_name
        to_arm = [n for n in names if n not in by_name]
        log.info(
            "surge: arming pre-staging of %s on spare(s) %s "
            "(await bounded at %.0fs)", mode, to_arm,
            self.prestage_timeout_s,
        )
        for gid, ns in spares:
            if any(n in to_arm for n in ns):
                # The taint FIRST: the spare must be unschedulable for
                # exactly its (pre-staged) flip window, like a plain
                # surge flip — arming without it would bounce a node
                # still receiving workloads.
                self._taint_surge(ns, add=True)
        for name in to_arm:
            try:
                self.retry_policy.call(
                    lambda name=name: self.api.patch_node_annotations(
                        name, {labels_mod.PRESTAGE_ANNOTATION: mode}
                    ),
                    op="rollout.prestage_arm",
                    classify=classify_kube_error,
                )
            except KubeApiError as e:
                if e.status != 404:
                    raise
                # Drop the vanished node from the await set too: leaving
                # it in `names` would stall the whole prestage phase for
                # the full timeout on a node that provably cannot answer
                # (the flip window's await retires it as deleted).
                log.warning(
                    "node %s vanished before its prestage arm "
                    "(autoscaler scale-down); skipping it in the "
                    "prestage await", name,
                )
                names.remove(name)
        retry_mod.poll_until(
            scan, self.prestage_timeout_s, self.poll_interval_s
        )
        if len(by_name) < len(names):
            log.warning(
                "surge: %d spare(s) never reported pre-staged within "
                "%.0fs (%s); their flip window falls back to the full "
                "flip path",
                len(names) - len(by_name), self.prestage_timeout_s,
                sorted(set(names) - set(by_name)),
            )
        return by_name

    def prestage_spares(self, mode: str) -> dict:
        """Arm + await spare pre-staging WITHOUT flipping anything — the
        ahead-of-the-rollout half of zero-bounce flips (``ctl rollout
        --prestage-only``): pre-stage while the pool is still serving at
        full capacity (the pre-staging overlaps the preceding wave of
        live traffic, or a preceding rollout), then run the real
        ``--surge --prestage`` rollout, whose spare window opens
        instantly. Picks the same greedy plan-order spares the surge
        phase will pick. The surge taint is KEPT on armed spares — they
        hold a non-desired mode; the real rollout reclaims it when they
        converge."""
        mode = canonical_mode(mode)
        if mode not in VALID_MODES:
            raise ValueError(
                f"invalid CC mode {mode!r} (valid: {VALID_MODES})"
            )
        if self.surge <= 0:
            raise ValueError("prestage_spares requires surge > 0")
        if self.informer is not None and not self.informer.synced:
            self.informer.start()
            if not self.informer.wait_for_sync(60.0):
                raise KubeApiError(
                    None, "informer cache never synced; refusing to "
                    "pre-stage over a possibly-empty pool view"
                )
        listing = self._list_pool()
        quarantined = set(self._quarantined_of(listing))
        listing = [
            n for n in listing
            if n["metadata"]["name"] not in quarantined
        ]
        labels_by_name = {
            n["metadata"]["name"]: node_labels(n) for n in listing
        }
        groups = [
            (gid, names)
            for gid, names in plan_groups(
                self.api, self.selector, nodes=listing
            )
            if not all(
                labels_by_name.get(n, {}).get(CC_MODE_LABEL) == mode
                and labels_by_name.get(n, {}).get(CC_MODE_STATE_LABEL) == mode
                for n in names
            )
        ]
        spares, _rest = self._pick_spares(groups)
        names = sorted(n for _, ns in spares for n in ns)
        if not spares:
            log.warning(
                "prestage: surge=%d but no group fits the spare budget; "
                "nothing to arm", self.surge,
            )
            return {
                "mode": mode, "spares": [], "prestaged": [],
                "seconds": 0.0, "ok": False,
            }
        t0 = time.monotonic()
        prestaged = self._prestage_phase(mode, spares)
        for gid, ns in spares:
            for name in ns:
                rec = prestaged.get(name)
                if rec is not None:
                    self._fl(
                        flight_mod.EVENT_SPARE_PRESTAGED, node=name,
                        group=gid, seconds=rec.get("seconds"),
                    )
        if prestaged:
            self._crash_point("spare-prestaged")
        return {
            "mode": mode,
            "spares": names,
            "prestaged": sorted(prestaged),
            "seconds": round(time.monotonic() - t0, 3),
            "ok": len(prestaged) == len(names),
        }

    # -- continuous prestage (whole-fleet zero-bounce) ---------------------

    def _prestage_allowance(self) -> int:
        """How many nodes may be in prestage transition right now: the
        headroom gate's knee slack (whole nodes the offered load leaves
        free under the serving knee — serve.sweep.knee_slack_nodes),
        capped at ``max_unavailable`` so concurrent prestages can never
        violate the rollout's own disruption bound. No gate =
        max_unavailable. A gate that RAISES reads ZERO slack
        (fail-closed, the mirror image of the SLO gate's fail-open):
        prestage is an optimization, and it must never consume headroom
        it cannot prove exists — the wave rolls on unpaced either way."""
        if self.headroom_gate is None:
            allowance = self.max_unavailable
        else:
            try:
                slack = int(self.headroom_gate())
            except Exception as e:  # noqa: BLE001 - fail-closed by design
                log.warning(
                    "prestage headroom gate failed (%s); reading ZERO slack "
                    "(prestage pauses; the wave is never paused by this)", e,
                )
                return 0
            allowance = max(0, min(slack, self.max_unavailable))
        # A fail-slow suspect's capacity is phantom headroom: it still
        # answers probes, but its effective token rate is a fraction of
        # what the knee model assumes. Deduct suspects from the slack so
        # prestage never spends headroom a gray node only pretends to
        # supply.
        if self._failslow_suspects:
            allowance = max(0, allowance - len(self._failslow_suspects))
        return allowance

    def _prestage_adopt(self, mode, groups, record) -> None:
        """Resume-time ledger adoption — the dual-wave resume. Every
        checkpointed entry is re-validated against the CURRENT plan: a
        matching plan digest is adopted AS-IS and re-stamped with this
        run's fence generation (no re-reserve — ``reserve()`` refusing
        an existing node IS the no-double-charge proof), while a
        vanished group or a digest mismatch is invalidated and released
        exactly once, aborting the agent's hold so the node re-flips
        via the full path rather than converging against an old plan.
        Mirrors the surge resume rule: a kill between prestage-armed
        and the flip adopts the held node, never re-drives it."""
        ledger = self._ledger
        plan = {gid: names for gid, names in groups}
        digests = {
            gid: rollout_state.plan_digest(mode, gid, names)
            for gid, names in plan.items()
        }
        adopted: list[str] = []
        dropped: list[str] = []
        with self._record_lock:
            for name in list(ledger.entries):
                entry = ledger.entry(name)
                gid = str(entry.get("gid"))
                names = plan.get(gid)
                if names is None or name not in names:
                    # The group left the remaining plan: it either
                    # converged before the crash (the charge settles as
                    # converged) or was quarantined out from under its
                    # prestage (invalidated; abort the hold).
                    done = (record.done.get(gid) or {}) if record else {}
                    outcome = (
                        "converged" if done.get("ok") else "invalidated"
                    )
                    if outcome == "invalidated":
                        self._prestage_clear_arm(name)
                    ledger.release(name)
                    self.metrics.record_prestage(outcome)
                    self._fl(
                        flight_mod.EVENT_PRESTAGE_RELEASED, node=name,
                        outcome=outcome, resumed=True,
                    )
                    dropped.append(name)
                elif entry.get("digest") != digests[gid]:
                    self._prestage_clear_arm(name)
                    ledger.release(name)
                    self.metrics.record_prestage("invalidated")
                    self._fl(
                        flight_mod.EVENT_PRESTAGE_INVALIDATED, node=name,
                        outcome="invalidated", resumed=True,
                    )
                    dropped.append(name)
                else:
                    ledger.mark(
                        name, entry.get("state"),
                        generation=self.generation,
                    )
                    adopted.append(name)
        if adopted or dropped:
            log.warning(
                "resume: capacity ledger adopted %d prestage entr%s "
                "as-is (%s) and released %d stale one(s) (%s)",
                len(adopted), "y" if len(adopted) == 1 else "ies",
                sorted(adopted), len(dropped), sorted(dropped),
            )

    def _prestage_maintain(self, mode, groups, i, record, window_id) -> None:
        """One maintenance pass per wave boundary, run BEFORE the window
        timer starts (prestage awaits never count against the measured
        disruption wall): (1) sustained SLO burn pauses prestage — and
        ONLY prestage; the wave itself is paced by ``_slo_gate_wait``;
        (2) top-up — reserve + arm upcoming groups in plan order,
        current window first, while the allowance holds; (3) finalize
        the current window's entries — adopt the agents' held records
        or invalidate and fall back to the full flip path; (4) a second
        top-up fills the transition slots the finalize freed, which is
        what makes wave N+1 prestage WHILE window N flips."""
        window = groups[i : i + self.max_unavailable]
        paused = self._slo_breached()
        allowance = self._prestage_allowance()
        self.metrics.set_prestage_headroom_nodes(allowance)
        if paused:
            log.warning(
                "SLO burn at window %s boundary: pausing prestage "
                "top-up (the wave itself is paced separately)",
                window_id,
            )
            self.metrics.record_prestage("paused")
            self._fl(
                flight_mod.EVENT_PRESTAGE_PAUSED, window=window_id,
                reason="slo-burn",
            )
        else:
            self._prestage_topup(
                mode, groups, i, record, allowance, window_id
            )
        self._prestage_finalize(mode, window, record, window_id)
        if not paused:
            self._prestage_topup(
                mode, groups, i + self.max_unavailable, record,
                allowance, window_id,
            )
        self.metrics.set_prestage_reserved(self._ledger.in_transition())

    def _prestage_topup(
        self, mode, groups, start, record, allowance, window_id
    ) -> None:
        """Reserve + arm groups from ``groups[start:]`` in plan order
        while transition headroom remains. A slice flips as one unit,
        so a group reserves ALL its nodes or none (too-big groups are
        skipped, not split — the scan keeps looking for one that
        fits). Groups already in the ledger only get stranded
        reserved-not-armed entries re-armed (the prestage-reserved
        crash resume)."""
        ledger = self._ledger
        for j in range(start, len(groups)):
            gid, names = groups[j]
            entered = [n for n in names if ledger.entry(n) is not None]
            if entered:
                stranded = [
                    n for n in entered
                    if (ledger.entry(n) or {}).get("state")
                    == rollout_state.LEDGER_RESERVED
                ]
                if stranded:
                    self._prestage_arm(
                        mode, gid, stranded, record, window_id
                    )
                continue
            if self._failslow_suspects and any(
                n in self._failslow_suspects for n in names
            ):
                # A suspect group is never prestaged: its drain handoff
                # would route in-flight work through a node already
                # serving at a fraction of its rate, and a confirmed
                # verdict is about to skip the group anyway.
                continue
            free = allowance - ledger.in_transition()
            if free <= 0:
                break
            if len(names) > free:
                continue
            digest = rollout_state.plan_digest(mode, gid, names)
            with self._record_lock:
                for name in names:
                    ledger.reserve(
                        name, gid, digest, self.generation or 0,
                        limit=allowance,
                    )
            for name in names:
                self.metrics.record_prestage("reserved")
                self._fl(
                    flight_mod.EVENT_PRESTAGE_RESERVED, node=name,
                    group=gid, window=window_id, digest=digest,
                )
            # The reservation is durable BEFORE the node is touched: a
            # kill at the point below leaves a charged entry the
            # successor adopts, never a second charge.
            self._checkpoint(record)
            self._crash_point("prestage-reserved")
            self._prestage_arm(mode, gid, names, record, window_id)

    def _prestage_arm(self, mode, gid, names, record, window_id) -> None:
        """Arm the PRESTAGE annotation on regular nodes — NO surge
        taint: the node keeps serving, and the drain inside the agent's
        journaled flip hands its in-flight requests to peers (the PR-14
        handoff path), which is exactly the capacity the ledger
        reserved. A vanished node (404) releases its charge as degraded
        — its window retires it."""
        ledger = self._ledger
        armed: list[str] = []
        for name in names:
            try:
                self.retry_policy.call(
                    lambda name=name: self.api.patch_node_annotations(
                        name, {labels_mod.PRESTAGE_ANNOTATION: mode}
                    ),
                    op="rollout.prestage_arm",
                    classify=classify_kube_error,
                )
                armed.append(name)
            except KubeApiError as e:
                if e.status != 404:
                    raise
                log.warning(
                    "node %s vanished before its prestage arm "
                    "(autoscaler scale-down); releasing its ledger "
                    "charge", name,
                )
                with self._record_lock:
                    ledger.release(name)
                self.metrics.record_prestage("degraded")
                self._fl(
                    flight_mod.EVENT_PRESTAGE_RELEASED, node=name,
                    outcome="degraded", window=window_id,
                )
        if not armed:
            return
        with self._record_lock:
            for name in armed:
                ledger.mark(
                    name, rollout_state.LEDGER_ARMED,
                    generation=self.generation,
                )
        for name in armed:
            self.metrics.record_prestage("armed")
            self._fl(
                flight_mod.EVENT_PRESTAGE_ARMED, node=name, group=gid,
                window=window_id,
            )
        self._checkpoint(record)
        self._crash_point("prestage-armed")

    def _prestage_finalize(self, mode, window, record, window_id) -> None:
        """The current window's entries meet their flip window: adopt
        the agents' held records (entry → held; the node flips in
        ~drain+readmit and its transition headroom is freed — held
        entries cost nothing, which is what lets the next top-up start
        wave N+1), or invalidate. Digest drift and never-held timeouts
        both downgrade the node to the PR-10 full flip path and the
        rollout presses on — a prestage-path failure never halts."""
        ledger = self._ledger
        pending: list[str] = []
        for gid, names in window:
            digest = rollout_state.plan_digest(mode, gid, names)
            for name in names:
                entry = ledger.entry(name)
                if entry is None:
                    continue
                if entry.get("digest") != digest:
                    # The plan advanced under the entry: a stale
                    # prestage must re-flip, never converge against an
                    # old plan.
                    self._prestage_invalidate(
                        name, record, window_id, outcome="invalidated"
                    )
                elif entry.get("state") != rollout_state.LEDGER_HELD:
                    pending.append(name)
        if not pending:
            return
        held: set[str] = set()

        def scan() -> bool:
            nodes = {
                n["metadata"]["name"]: n for n in self._list_pool()
            }
            for name in pending:
                if name in held:
                    continue
                node = nodes.get(name)
                if node is not None and (
                    self._prestaged_record_of(node, mode) is not None
                ):
                    held.add(name)
            return len(held) == len(pending)

        retry_mod.poll_until(
            scan, self.prestage_timeout_s, self.poll_interval_s
        )
        with self._record_lock:
            for name in held:
                ledger.mark(name, rollout_state.LEDGER_HELD)
        for name in held:
            self.metrics.record_prestage("held")
            self._fl(
                flight_mod.EVENT_PRESTAGE_HELD, node=name,
                window=window_id,
            )
        for name in pending:
            if name not in held:
                self._prestage_invalidate(
                    name, record, window_id, outcome="degraded"
                )
        if held:
            self._checkpoint(record)

    def _prestage_invalidate(
        self, name, record, window_id, outcome
    ) -> None:
        """Exactly-once invalidation: the crash point fires FIRST (a
        kill here leaves the charged entry for the successor to
        re-validate and release — never a lost or doubled charge), then
        the agent's hold is aborted, the charge released, and the
        balanced ledger checkpointed."""
        self._crash_point("prestage-invalidate")
        log.warning(
            "prestage of %s invalidated (%s); the node re-flips via "
            "the full path", name, outcome,
        )
        self._prestage_clear_arm(name)
        with self._record_lock:
            self._ledger.release(name)
        self.metrics.record_prestage(outcome)
        self._fl(
            flight_mod.EVENT_PRESTAGE_INVALIDATED, node=name,
            window=window_id, outcome=outcome,
        )
        self._checkpoint(record)

    def _prestage_release_group(self, names, outcome, window) -> None:
        """Release the entries of a just-awaited window group (held
        prestages settle as converged). Idempotent: release() answers
        False for absent nodes, so only real releases are journaled."""
        with self._record_lock:
            released = [n for n in names if self._ledger.release(n)]
        for name in released:
            self.metrics.record_prestage(outcome)
            self._fl(
                flight_mod.EVENT_PRESTAGE_RELEASED, node=name,
                outcome=outcome, window=window,
            )
        if released:
            self.metrics.set_prestage_reserved(
                self._ledger.in_transition()
            )

    def _prestage_clear_arm(self, name: str) -> None:
        """Best-effort abort of a node's prestage hold: deleting the
        PRESTAGE annotation makes the agent revert its held flip
        (manager.py watches the request vanish). A vanished node needs
        no abort."""
        try:
            self.retry_policy.call(
                lambda: self.api.patch_node_annotations(
                    name, {labels_mod.PRESTAGE_ANNOTATION: None}
                ),
                op="rollout.prestage_clear",
                classify=classify_kube_error,
            )
        except KubeApiError as e:
            if e.status != 404:
                raise

    # -- autoscaler scale-up adoption -------------------------------------

    def _adopt_new_nodes(
        self,
        mode: str,
        record,
        results: list[GroupResult],
        window_seconds: list[float],
        known: set[str],
    ) -> tuple[list[str], bool, str | None]:
        """Nodes created mid-rollout (autoscaler scale-up) that match the
        selector: adopt them into trailing windows — desired mode plus
        the rollout generation label — instead of silently leaving them
        at whatever mode their image booted with. Scans repeat until one
        finds nothing new, so a node created DURING the trailing window
        is adopted by the next scan. Returns (adopted node names, every
        adopted group converged, halted reason or None)."""
        adopted: list[str] = []
        ok = True
        while True:
            listing = self._list_pool()
            quarantined = set(self._quarantined_of(listing))
            # Same boundary re-check as the other window loops: a pool
            # that started bleeding nodes during the trailing adoption
            # phase must stop being reconfigured — the fleet-level
            # circuit breaker applies to adopted windows too.
            if self.failure_budget is not None:
                with self._record_lock:
                    if record is not None:
                        record.charge_budget(quarantined)
                    spend = self._spend(record, quarantined)
                if self._budget_exceeded(spend):
                    self._checkpoint(record, status=rollout_state.RECORD_HALTED)
                    self._fl(
                        flight_mod.EVENT_HALT,
                        reason="failure-budget-exceeded",
                        spend=spend, at="adoption-scan",
                    )
                    return sorted(adopted), False, "failure-budget-exceeded"
            fresh = [
                n for n in listing
                if n["metadata"]["name"] not in known
                and n["metadata"]["name"] not in quarantined
            ]
            known.update(quarantined)
            if not fresh:
                return sorted(adopted), ok, None
            groups = plan_groups(self.api, self.selector, nodes=fresh)
            names_flat = [n for _, ns in groups for n in ns]
            known.update(names_flat)
            log.warning(
                "adopting %d node(s) created mid-rollout (autoscaler "
                "scale-up) into a trailing wave: %s",
                len(names_flat), names_flat,
            )
            for name in names_flat:
                self._fl(
                    flight_mod.EVENT_NODE_ADOPTED, node=name, wave="adopt",
                )
            self.metrics.record_node_adoption(len(names_flat))
            with self._record_lock:
                if record is not None:
                    record.groups = list(record.groups) + list(groups)
            for i in range(0, len(groups), self.max_unavailable):
                if i and self.failure_budget is not None:
                    # Same boundary re-check as the other window loops:
                    # a multi-window adoption scan must not keep
                    # flipping windows after the budget blows mid-scan.
                    fresh = self._quarantined_of(self._list_pool())
                    with self._record_lock:
                        if record is not None:
                            record.charge_budget(fresh)
                        spend = self._spend(record, fresh)
                    if self._budget_exceeded(spend):
                        self._checkpoint(
                            record, status=rollout_state.RECORD_HALTED
                        )
                        self._fl(
                            flight_mod.EVENT_HALT,
                            reason="failure-budget-exceeded",
                            spend=spend, at="adoption-window",
                        )
                        return (
                            sorted(adopted), False,
                            "failure-budget-exceeded",
                        )
                window = groups[i : i + self.max_unavailable]
                window_id = i // self.max_unavailable
                # Adopted windows are real disruption too: the SLO gate
                # paces them exactly like the main loops.
                if not self._slo_gate_wait(wave="adopt", window=window_id):
                    self._checkpoint(
                        record, status=rollout_state.RECORD_HALTED
                    )
                    return sorted(adopted), False, "slo-burn-exceeded"
                self._crash_point("window-start")
                started = time.monotonic()
                self._note_window_inflight(len(window))
                self._fl(
                    flight_mod.EVENT_WINDOW_OPEN, wave="adopt",
                    window=window_id, groups=[gid for gid, _ in window],
                )
                for gid, names in window:
                    self._set_desired(
                        names, mode, wave="adopt", window=window_id
                    )
                self._crash_point("mid-window")
                window_failed = []
                for gid, names in window:
                    gres = self._await_group(gid, names, mode, started)
                    results.append(gres)
                    self._fl_group(gres, mode, wave="adopt", window=window_id)
                    with self._record_lock:
                        if record is not None:
                            record.note_group(
                                gid, gres.ok, gres.states, gres.seconds
                            )
                            if not gres.ok:
                                record.charge_budget(
                                    n for n, s in gres.states.items()
                                    if s not in (mode, STATE_NODE_DELETED)
                                )
                    adopted.extend(gres.nodes)
                    if not gres.ok:
                        window_failed.append(gid)
                self._note_window_inflight(-len(window))
                window_seconds.append(time.monotonic() - started)
                self._fl(
                    flight_mod.EVENT_WINDOW_CLOSE, wave="adopt",
                    window=window_id,
                    seconds=round(time.monotonic() - started, 3),
                    failed=window_failed or None,
                )
                self._crash_point("awaited")
                self._checkpoint(record)
                self._crash_point("window-boundary")
                if window_failed:
                    ok = False
                    if not self.continue_on_failure:
                        log.error(
                            "adopted group(s) %s failed; stopping the "
                            "trailing adoption wave", window_failed,
                        )
                        self._fl(
                            flight_mod.EVENT_HALT, reason="group-failed",
                            failed=window_failed, wave="adopt",
                            window=window_id,
                        )
                        return sorted(adopted), ok, None

    # -- sharded rollout waves --------------------------------------------

    def _rollout_waves(
        self,
        mode: str,
        groups: list[tuple[str, tuple[str, ...]]],
        labels_by_name: dict[str, dict],
        record,
        results: list[GroupResult],
        window_seconds: list[float],
        quarantined: list[str],
        resumed: bool,
        surged: list[str],
        known_nodes: set[str],
        surge_ok: bool = True,
    ) -> RolloutResult:
        """Drive the plan as up to ``wave_shards`` concurrent sub-rollouts
        (zone-partitioned, each strictly rolling at ``max_unavailable``),
        under ONE failure budget, ONE lease and ONE checkpointed record.
        Total in-flight disruption is bounded by wave_shards ×
        max_unavailable; within a zone the old one-window-at-a-time
        guarantee holds unchanged."""
        waves = partition_waves(groups, labels_by_name, self.wave_shards)
        log.info(
            "sharded rollout: %d group(s) across %d wave(s) "
            "(max_unavailable=%d per wave)",
            len(groups), len(waves), self.max_unavailable,
        )
        shared = {
            "lock": locks_mod.make_lock("rolling.waves-shared"),
            "halt": threading.Event(),
            "results": results,
            "window_seconds": window_seconds,
            # Seeded with the surge verdict: a failed spare under
            # continue_on_failure presses on but must fail the rollout.
            "ok": surge_ok,
            # A surge phase already charged the budget: every wave
            # re-checks before its FIRST window too.
            "surge_ran": bool(surged),
            "halted_reason": None,
            "initial_quarantined": list(quarantined),
            "fresh_quarantined": set(),
            "error": None,
        }
        threads = []
        for wid, wave in enumerate(waves):
            t = threading.Thread(
                # in_current_context: thread targets do not inherit
                # contextvars, and without the snapshot every span a
                # wave opens (rollout.group and the agents stitched
                # under it) would mint its own root trace instead of
                # nesting under the rollout root — /tracez could never
                # render the sharded rollout as one tree.
                target=obs_trace.in_current_context(
                    self._drive_wave_guarded, wid, wave, mode, record,
                    shared,
                ),
                name=f"rollout-wave-{wid}",
                daemon=True,
            )
            threads.append(t)
            t.start()
        for t in threads:
            t.join()
        if shared["error"] is not None:
            # First wave-thread death (OrchestratorKilled in chaos runs,
            # RolloutFenced after a lease loss, unexpected bugs alike)
            # re-raised in the caller's thread so the crash/fence
            # semantics match the single-shard orchestrator exactly.
            raise shared["error"]
        ok = shared["ok"] and not shared["halt"].is_set()
        adopted: list[str] = []
        if self.adopt_new_nodes and (ok or self.continue_on_failure):
            adopted, adopt_ok, adopt_halted = self._adopt_new_nodes(
                mode, record, results, window_seconds, known_nodes
            )
            ok = ok and adopt_ok
            if adopt_halted and shared["halted_reason"] is None:
                shared["halted_reason"] = adopt_halted
        self._checkpoint(
            record,
            status=(
                rollout_state.RECORD_COMPLETE if ok
                else rollout_state.RECORD_HALTED
            ),
        )
        self._federation_push_status(
            record,
            rollout_state.RECORD_COMPLETE if ok
            else rollout_state.RECORD_HALTED,
            reason=shared["halted_reason"],
        )
        return RolloutResult(
            mode=mode, ok=ok, groups=list(results),
            window_seconds=list(window_seconds),
            skipped_quarantined=sorted(
                set(quarantined) | shared["fresh_quarantined"]
            ),
            halted_reason=shared["halted_reason"],
            resumed=resumed, generation=self.generation,
            retired_deleted=self._deleted_of(results),
            adopted=adopted, surged=surged,
            max_unavailable_observed=self._max_inflight_observed,
        )

    def _drive_wave_guarded(self, wid, wave, mode, record, shared) -> None:
        try:
            self._drive_wave(wid, wave, mode, record, shared)
        except BaseException as e:  # noqa: BLE001  # cclint: crash-ok(first wave death wins - rollout re-raises it after halting every wave)
            with shared["lock"]:
                if shared["error"] is None:
                    shared["error"] = e
            shared["halt"].set()

    def _drive_wave(self, wid, wave, mode, record, shared) -> None:
        for i in range(0, len(wave), self.max_unavailable):
            if shared["halt"].is_set():
                return
            if (
                (i or shared.get("surge_ran"))
                and self.failure_budget is not None
            ):
                # Same boundary re-check as the single-shard loop; with an
                # informer this is a cache read, so N waves re-checking
                # costs the apiserver nothing.
                fresh = self._quarantined_of(self._list_pool())
                with self._record_lock:
                    if record is not None:
                        record.charge_budget(fresh)
                    spend = self._spend(
                        record, shared["initial_quarantined"], fresh
                    )
                if self._budget_exceeded(spend):
                    with shared["lock"]:
                        shared["halted_reason"] = "failure-budget-exceeded"
                        shared["fresh_quarantined"].update(fresh)
                        shared["ok"] = False
                    shared["halt"].set()
                    self._checkpoint(
                        record, status=rollout_state.RECORD_HALTED
                    )
                    self._fl(
                        flight_mod.EVENT_HALT,
                        reason="failure-budget-exceeded",
                        spend=spend, wave=wid, at="wave-boundary",
                    )
                    return
            window = wave[i : i + self.max_unavailable]
            window_id = i // self.max_unavailable
            if i or shared.get("surge_ran"):
                # Wave-boundary parent exchange, same contract as the
                # single-shard loop: spend up, global spend folded back,
                # parent halt honored by EVERY wave at its next
                # boundary. A RolloutFenced (stale regional lease or
                # parent generation) propagates through the guarded
                # runner and re-raises in the caller, exactly like a
                # single-shard fence.
                fed_reason = self._federation_sync(
                    record, wave=wid, window=window_id
                )
                if fed_reason is not None:
                    with shared["lock"]:
                        if shared["halted_reason"] is None:
                            shared["halted_reason"] = fed_reason
                        shared["ok"] = False
                    shared["halt"].set()
                    self._checkpoint(
                        record, status=rollout_state.RECORD_HALTED
                    )
                    self._fl(
                        flight_mod.EVENT_HALT, reason=fed_reason,
                        wave=wid, at="federation-boundary",
                    )
                    return
            # SLO pacing, stop-aware: a pause interrupted by another
            # wave's halt just stops; a pause that outlasts the budget
            # halts EVERY wave at its next boundary, like the failure
            # budget does.
            if not self._slo_gate_wait(
                wave=wid, window=window_id, stop=shared["halt"]
            ):
                if not shared["halt"].is_set():
                    with shared["lock"]:
                        shared["halted_reason"] = "slo-burn-exceeded"
                        shared["ok"] = False
                    shared["halt"].set()
                    self._checkpoint(
                        record, status=rollout_state.RECORD_HALTED
                    )
                return
            self._crash_point("window-start")
            started = time.monotonic()
            self._note_window_inflight(len(window))
            self._fl(
                flight_mod.EVENT_WINDOW_OPEN, wave=wid, window=window_id,
                groups=[gid for gid, _ in window],
            )
            for gid, names in window:
                self._set_desired(names, mode, wave=wid, window=window_id)
            self._crash_point("mid-window")
            window_failed = []
            for gid, names in window:
                gres = self._await_group(gid, names, mode, started)
                with shared["lock"]:
                    shared["results"].append(gres)
                self._fl_group(gres, mode, wave=wid, window=window_id)
                with self._record_lock:
                    if record is not None:
                        record.note_group(
                            gid, gres.ok, gres.states, gres.seconds
                        )
                        if not gres.ok:
                            # Same retire-don't-charge rule as the
                            # single-shard loop: scale-down ≠ CC failure.
                            record.charge_budget(
                                n for n, s in gres.states.items()
                                if s not in (mode, STATE_NODE_DELETED)
                            )
                if not gres.ok:
                    window_failed.append(gid)
            self._note_window_inflight(-len(window))
            with shared["lock"]:
                shared["window_seconds"].append(time.monotonic() - started)
            self._fl(
                flight_mod.EVENT_WINDOW_CLOSE, wave=wid, window=window_id,
                seconds=round(time.monotonic() - started, 3),
                failed=window_failed or None,
            )
            self._crash_point("awaited")
            self._checkpoint(record)
            self._crash_point("window-boundary")
            if window_failed:
                with shared["lock"]:
                    shared["ok"] = False
                if not self.continue_on_failure:
                    log.error(
                        "wave %d: group(s) %s failed; halting the rollout "
                        "(all waves stop at their next boundary)",
                        wid, window_failed,
                    )
                    self._fl(
                        flight_mod.EVENT_HALT, reason="group-failed",
                        failed=window_failed, wave=wid, window=window_id,
                    )
                    shared["halt"].set()
                    return

    # -- internals --------------------------------------------------------

    def _rollback(
        self, results: list[GroupResult], prior: dict[str, str | None]
    ) -> list[GroupResult]:
        """Revert groups that converged OK to their pre-rollout desired
        mode, newest first (the failed group itself is left for the
        operator — re-driving a slice that just failed would thrash it).

        Nodes whose prior label was absent get the label removed; their
        agents re-apply the default mode, which depends on host capability,
        so convergence is only awaited where the prior mode is known.
        Skipped groups are left alone: this rollout never bounced them,
        so it has no business reverting them (and for record-resumed
        skips the pre-rollout mode died with the first orchestrator)."""
        rolled_back: list[GroupResult] = []
        for gres in reversed([g for g in results if g.ok and not g.skipped]):
            modes = {prior.get(n) for n in gres.nodes}
            log.warning(
                "rolling back group %s to prior desired mode(s) %s",
                gres.group, sorted(str(m) for m in modes),
            )
            started = time.monotonic()
            for name in gres.nodes:
                self.api.patch_node_labels(name, {CC_MODE_LABEL: prior.get(name)})
            # Await each node against ITS OWN prior mode (they may differ
            # within a slice); absent priors can't be awaited — the default
            # mode the agent re-applies depends on host capability.
            ok = True
            states: dict[str, str] = {}
            for name in gres.nodes:
                prior_mode = prior.get(name)
                prior_mode = canonical_mode(prior_mode) if prior_mode else None
                if prior_mode in VALID_MODES:
                    nres = self._await_group(
                        gres.group, (name,), prior_mode, started
                    )
                    ok = ok and nres.ok
                    states.update(nres.states)
                else:
                    states[name] = "reverted-unawaited"
            rolled_back.append(
                GroupResult(
                    group=gres.group, nodes=gres.nodes, ok=ok,
                    seconds=time.monotonic() - started, states=states,
                )
            )
        return rolled_back

    def _set_desired(
        self, names: tuple[str, ...], mode: str,
        wave: int | str | None = None, window: int | str | None = None,
    ) -> None:
        # Cross-process trace stitching: the current span (the rollout
        # root, or a wave thread's context snapshot of it) rides in the
        # SAME patch as the desired mode, so the node agent's reconcile
        # adopts it as its root span's remote parent — one causal tree
        # from `ctl rollout` down through each node's drain/reset/smoke.
        sp = obs_trace.current_span()
        parent = obs_trace.format_parent(sp) if sp is not None else None
        for name in names:
            log.info("setting %s=%s on %s", CC_MODE_LABEL, mode, name)
            patch: dict = {CC_MODE_LABEL: mode}
            if parent is not None:
                patch[labels_mod.ROLLOUT_TRACE_LABEL] = parent
            if self.generation is not None:
                # Every fenced write records which rollout generation
                # drove it — a successor (or `tpu-cc-ctl status`) can see
                # at a glance whether a node's desired mode came from the
                # live rollout or a fenced-out predecessor.
                patch[rollout_state.ROLLOUT_GEN_LABEL] = str(self.generation)
            try:
                self.api.patch_node_labels(name, patch)
                self._fl(
                    flight_mod.EVENT_NODE_DESIRED, node=name, mode=mode,
                    wave=wave, window=window,
                )
            except KubeApiError as e:
                if e.status != 404:
                    raise
                # Scale-down raced the window start: the Node object is
                # already gone. Not a failure — the await's fallback GET
                # resolves the slot as deleted on its first poll.
                log.warning(
                    "node %s vanished before its desired-mode write "
                    "(autoscaler scale-down); it will be retired from "
                    "the window", name,
                )

    def _note_window_inflight(self, delta: int) -> None:
        """Track concurrently mid-flip (non-surge) groups across every
        wave thread; the max is the rollout's measured disruption."""
        with self._inflight_lock:
            self._inflight_groups += delta
            self._max_inflight_observed = max(
                self._max_inflight_observed, self._inflight_groups
            )

    @staticmethod
    def _deleted_of(results: list[GroupResult]) -> list[str]:
        return sorted({
            n
            for g in results
            for n, s in g.states.items()
            if s == STATE_NODE_DELETED
        })

    def _pending_states(self, names: list[str]) -> dict[str, str | None]:
        """Current state-label values for ``names``: from the informer
        cache when present (zero apiserver round trips per poll — the
        O(pool)→O(changes) hinge of the whole refactor), else from ONE
        selector listing (per-node GETs are O(pool) round trips per poll;
        the listing is a single one whatever the pool size). A node
        missing from the view — its selector label edited mid-rollout, or
        its Node object deleted by the autoscaler — falls back to a
        direct GET rather than silently reading as pending; a 404 there
        resolves the slot as :data:`STATE_NODE_DELETED` so a scale-down
        mid-window never burns the window deadline as a phantom
        timeout."""
        if self.informer is not None:
            # Indexed reads: O(group) per poll, not O(pool) — at 10k
            # nodes, rebuilding a pool-wide dict per settle-check would
            # reintroduce client-side the cost the cache removed
            # server-side.
            listed = {}
            for name in names:
                node = self.informer.get(name)
                if node is not None:
                    listed[name] = node_labels(node).get(CC_MODE_STATE_LABEL)
        else:
            listed: dict[str, str | None] = {
                n["metadata"]["name"]: node_labels(n).get(CC_MODE_STATE_LABEL)
                for n in self._list_pool()
            }
        return {
            name: (
                listed[name]
                if name in listed
                else self._state_or_deleted(name)
            )
            for name in names
        }

    def _state_or_deleted(self, name: str) -> str | None:
        """Direct state read for a node absent from the pool view: its
        selector label may merely have been edited (GET still answers),
        or the Node object is gone (404 → STATE_NODE_DELETED)."""
        try:
            node = self.retry_policy.call(
                lambda: self.api.get_node(name),
                op="rollout.get_node",
                classify=classify_kube_error,
            )
        except KubeApiError as e:
            if e.status == 404:
                return STATE_NODE_DELETED
            raise
        return node_labels(node).get(CC_MODE_STATE_LABEL)

    def _note_converge_seconds(self, seconds: float) -> None:
        """Append one node's convergence wall to the peer history the
        straggler wall is computed from (bounded; oldest evicted)."""
        with self._inflight_lock:
            self._converge_history.append(seconds)
            if len(self._converge_history) > 64:
                del self._converge_history[0]

    def _straggler_wall(self) -> float | None:
        """The peer-relative straggler deadline for the CURRENT window,
        or None while disarmed. Armed only once enough peers converged
        this rollout (min_peers) — the first window of a cold rollout
        has no peer baseline and must run on the absolute node timeout
        alone. The wall is median(peer walls) x factor, floored so a
        fast homogeneous fleet (medians near zero) cannot turn routine
        scheduling jitter into skips."""
        if self.straggler_factor is None:
            return None
        with self._inflight_lock:
            if len(self._converge_history) < self.straggler_min_peers:
                return None
            med = statistics.median(self._converge_history)
        return max(self.straggler_floor_s, self.straggler_factor * med)

    def _await_group(
        self, gid: str, names: tuple[str, ...], mode: str, started: float
    ) -> GroupResult:
        with obs_trace.span(
            "rollout.group", group=gid, nodes=list(names), mode=mode
        ) as sp:
            gres = self._await_group_inner(gid, names, mode, started)
            sp.set_attribute("ok", gres.ok)
            sp.set_attribute("states", gres.states)
            if not gres.ok:
                sp.status = obs_trace.STATUS_ERROR
            return gres

    def _await_group_inner(
        self, gid: str, names: tuple[str, ...], mode: str, started: float
    ) -> GroupResult:
        pending = set(names)
        states: dict[str, str] = {}
        # A 'failed' state already present at the FIRST poll is STALE — a
        # resumed rollout onto a previously-failed node would otherwise
        # halt instantly on the leftover label instead of giving the agent
        # its retry. Such nodes stay pending until the state changes (a
        # node that leaves 'failed' and returns to it failed freshly) — but
        # only for a bounded grace (a few polls): an agent that is down, or
        # re-fails without the label ever leaving 'failed' between polls,
        # is indistinguishable from stale, and letting it consume the full
        # node timeout turns every genuine failure on such a node into a
        # slow one (ADVICE r4 #5). After the grace, 'failed' is believed.
        stale: dict = {"failed": None}
        stale_grace_deadline = (
            time.monotonic()
            + self.STALE_FAILED_GRACE_POLLS * self.poll_interval_s
        )

        def group_settled() -> bool:
            """One poll pass; True once every node reached a terminal state."""
            if not pending:
                return True
            polled = self._pending_states(sorted(pending))
            stale_failed = stale["failed"]
            if stale_failed is None:
                stale_failed = stale["failed"] = {
                    n for n, s in polled.items() if s == STATE_FAILED
                }
            elif stale_failed and time.monotonic() >= stale_grace_deadline:
                log.warning(
                    "node(s) %s still 'failed' after the stale-failed "
                    "grace (%d polls) — treating as genuinely failed",
                    sorted(stale_failed), self.STALE_FAILED_GRACE_POLLS,
                )
                stale_failed.clear()
            for name, state in polled.items():
                if state != STATE_FAILED:
                    stale_failed.discard(name)
                if state == mode:
                    states[name] = state
                    pending.discard(name)
                    self._note_converge_seconds(
                        time.monotonic() - started
                    )
                elif state == STATE_NODE_DELETED:
                    # The Node object is gone (autoscaler scale-down):
                    # resolve the slot immediately — it is not a CC
                    # failure and must not wait out the window deadline.
                    log.warning(
                        "node %s was deleted mid-window; retiring it from "
                        "the rollout (no failure-budget charge)", name,
                    )
                    states[name] = state
                    pending.discard(name)
                elif state == STATE_FAILED and name not in stale_failed:
                    states[name] = state
                    pending.discard(name)
            # Peer-relative straggler wall: a node still pending long
            # after its peers' median convergence wall is a gray node,
            # not a slow one — cut it loose NOW (charged to the failure
            # budget like a failure, distinct state for forensics)
            # instead of letting one brownout chip hold the whole
            # disruption window open to the absolute node timeout.
            if pending:
                wall = self._straggler_wall()
                if wall is not None and time.monotonic() - started > wall:
                    for name in sorted(pending):
                        log.error(
                            "node %s exceeded the straggler wall "
                            "(%.1fs = %.1fx peer median) in group %s; "
                            "skipping it (budget-charged)",
                            name, wall, self.straggler_factor, gid,
                        )
                        states[name] = STATE_STRAGGLER
                        self._fl(
                            flight_mod.EVENT_STRAGGLER_SKIPPED,
                            node=name, group=gid,
                            wall_s=round(wall, 3),
                            waited_s=round(
                                time.monotonic() - started, 3
                            ),
                        )
                    pending.clear()
            return not pending

        remaining = max(0.0, started + self.node_timeout_s - time.monotonic())
        if self.informer is not None:
            # Event-driven await: wake on cache changes (plus a slow
            # recheck tick so the stale-failed grace clock still fires on
            # a quiet pool) instead of burning a listing per poll sleep.
            self.informer.wait_for(
                lambda _informer: group_settled(),
                remaining,
                recheck_interval_s=self.poll_interval_s,
            )
        else:
            retry_mod.poll_until(
                group_settled, remaining, self.poll_interval_s
            )
        for name in pending:  # timed out
            states[name] = "timeout"
        seconds = time.monotonic() - started
        # Deleted nodes are retired, not failed: a group whose only
        # non-converged members were scaled away still counts converged.
        ok = all(
            s in (mode, STATE_NODE_DELETED) for s in states.values()
        )
        (log.info if ok else log.error)(
            "group %s -> %s in %.1fs (states=%s)", gid,
            "converged" if ok else "FAILED", seconds, states,
        )
        return GroupResult(
            group=gid, nodes=names, ok=ok, seconds=seconds, states=states
        )
