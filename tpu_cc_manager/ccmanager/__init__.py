"""The control loop: reconciler, watch loop, rolling orchestrator, CLI.

Reference analogue: main.py (CCManager, watch_and_apply, main(); SURVEY.md §2
#1-#4, §3).
"""

from tpu_cc_manager.ccmanager.manager import CCManager

__all__ = ["CCManager"]
