"""End-to-end benchmark: per-node drain → CC-on → ready latency.

North-star metric (BASELINE.md): < 90 s per-node drain→CC-on→ready. The
reference publishes no numbers (SURVEY.md §6); 90 s is the target from
BASELINE.json and ``vs_baseline`` reports how many times under target we
land (value 9 s → vs_baseline 10.0).

Two scenarios run, both through the REAL reconcile pipeline (CCManager)
against the in-memory apiserver fake and the fake TPU device layer — pause
labels, pod-drain polling with an emulated operator controller,
stage/reset/wait, attestation fetch + verification, and the REAL JAX matmul
smoke workload in a subprocess on whatever accelerator this machine has
(the driver runs this on one real TPU chip):

- **control** (the headline ``value``): zero device latencies — measures the
  control plane's own overhead plus the end-to-end JAX verification, the
  part this framework is responsible for.
- **realistic**: the fake device is configured with defensible real-world
  latencies (30 s of reset work — modeled per-chip at 7.5 s × 4 so the
  bounded-pool parallel reset is measurable; 20 s boot — the order of a
  TPU runtime restart — and a 3 s pod-termination delay per the operator
  controller), so the <90 s claim is made against simulated-real device
  time, not zero-cost fakes. Since the pipeline overlaps phases, the
  summary carries explicit ``wall_seconds`` / ``sum_phase_seconds`` /
  ``overlap_saved_s`` accounting, and ``smoke_cold_s``/``smoke_warm_s``
  prove the persistent compilation cache across a simulated CC bounce.

The result is self-describing: smoke backend, chip generation, and MFU ride
along so the throughput number carries its own denominator.

Prints exactly one JSON line.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time


def _tpu_preflight(
    timeout_s: float = 90.0, attempts: int = 3, backoff_s: float = 20.0
) -> bool:
    """Probe the accelerator OUTSIDE the timed region, with bounded retry.

    A wedged TPU transport hangs dispatches without erroring; discovering
    that inside the timed reconcile would charge the hang + CPU retry to
    the drain→ready metric. Probe in a child process first and pin the
    smoke to CPU when the chip isn't usable.

    One failed probe is not proof the chip is gone — the tunnel's dispatch
    latency is erratic (12-75 s observed for identical work) and a single
    slow window at the wrong moment would silently degrade a whole round's
    evidence to CPU (this happened to every driver-run bench r1-r4). Retry
    with a pause between attempts; give up only when ``attempts`` probes
    in a row failed. Each probe is its own child process, so a hung
    attempt is abandoned, not killed mid-dispatch in-process.
    """
    probe = (
        "import jax, jax.numpy as jnp;"
        "print(float(jax.jit(lambda x: (x @ x).sum())(jnp.ones((128, 128)))))"
    )
    for attempt in range(max(1, attempts)):
        if attempt:
            print(
                f"# tpu preflight attempt {attempt} failed; retrying in "
                f"{backoff_s:.0f}s", file=sys.stderr,
            )
            time.sleep(backoff_s)
        try:
            proc = subprocess.run(
                [sys.executable, "-c", probe], capture_output=True,
                timeout=timeout_s, text=True,
            )
        except subprocess.TimeoutExpired:
            continue
        if proc.returncode == 0:
            return True
    return False


def _smoke_subprocess(
    workload: str, timeout_s: float, force_cpu: bool,
    extra_env: dict | None = None,
) -> dict:
    # Shared subprocess-smoke contract (tpu_cc_manager/smoke/runner.py);
    # imported lazily so the module parses before sys.path setup.
    from tpu_cc_manager.smoke.runner import run_workload_subprocess

    return run_workload_subprocess(
        workload, timeout_s=timeout_s, force_cpu=force_cpu,
        cwd=os.path.dirname(os.path.abspath(__file__)),
        extra_env=extra_env,
    )


def select_headline_smoke(
    smokes: list[dict], control_backend: str
) -> tuple[str, dict, list[dict]]:
    """Pick the chip-side smoke metrics the bench reports as its headline.

    Chip-side numbers (tflops/mfu) are stable run-to-run even when tunnel
    wall time is not, but taking them from the control run ALONE (r1-r4
    behavior) lets one noise-dominated run own the headline. Rule: prefer
    the best backend any run reached ("tpu" over CPU fallback), take the
    MEDIAN-by-tflops run on it, and return the full sorted list so the
    caller can disclose every raw value. If no run on that backend carries
    a timing (e.g. the one TPU run had timing_valid=false), fall back to
    the control run's OWN backend — never CPU numbers wearing the TPU
    label — and recompute the disclosure list for that backend.

    Returns (backend_label, headline_smoke, timed_runs_sorted). The first
    smoke in ``smokes`` must be the control run's.
    """
    control_smoke = smokes[0]
    best_backend = (
        "tpu" if any(s.get("backend") == "tpu" for s in smokes)
        else control_backend
    )

    def _timed_on(backend: str) -> list[dict]:
        return sorted(
            (s for s in smokes
             if s.get("backend") == backend and s.get("tflops") is not None),
            key=lambda s: s["tflops"],
        )

    timed = _timed_on(best_backend)
    if not timed:
        best_backend = control_backend
        timed = _timed_on(best_backend)
    smoke = timed[(len(timed) - 1) // 2] if timed else control_smoke
    return best_backend, smoke, timed


NS = "tpu-operator"

def phase_names() -> tuple[str, ...]:
    """Reconcile phases the per-phase histograms aggregate over, from the
    canonical constants (the journal also carries sub-spans like
    drain.await_pods; the headline sticks to the pipeline phases so
    rounds stay comparable). Imported lazily so the module parses before
    sys.path setup."""
    from tpu_cc_manager.utils import metrics as m

    return (
        m.PHASE_DRAIN, m.PHASE_STAGE, m.PHASE_BARRIER, m.PHASE_RESET,
        m.PHASE_WAIT_READY, m.PHASE_ATTEST, m.PHASE_SMOKE, m.PHASE_READMIT,
    )


def phase_accounting(
    phase_durations: dict, wall_seconds: float,
    smoke_compile_overlap_s: float = 0.0,
) -> dict:
    """Wall-vs-sum accounting for the pipelined reconcile.

    ``sum_phase_seconds`` is the serialized-equivalent cost: the sum of
    every pipeline phase's duration, with the reset phase replaced by the
    sum of the backend's per-chip ``reset.chip`` spans when those exist
    (a parallel per-chip reset's phase wall only shows the pool's wall
    time; the serial walk would have paid the per-chip sum), plus
    ``smoke_compile_overlap_s`` — the smoke warmup's compile span that
    ran hidden inside wait_ready (smoke/runner.py dispatch gate). Only
    the PRE-release part of the compile is added (the warmup handle
    reports exactly that as ``warmup_overlap_s``): any compile that
    spilled past the gate release already shows up inside the measured
    smoke phase, so the verify cost is never double-counted. The summary
    used to implicitly assume serialized phases — wrong the moment any
    two phases overlap — so the three numbers are now explicit:
    ``wall_seconds`` (what the node actually paid),
    ``sum_phase_seconds`` (what the serial pipeline would have paid), and
    ``overlap_saved_s`` (their difference, floored at 0)."""
    serial_sum = sum(
        sum(phase_durations.get(p, ())) for p in phase_names()
    )
    chip_spans = phase_durations.get("reset.chip", ())
    if chip_spans:
        reset_wall = sum(phase_durations.get("reset", ()))
        serial_sum += max(0.0, sum(chip_spans) - reset_wall)
    serial_sum += max(0.0, smoke_compile_overlap_s)
    return {
        "wall_seconds": round(wall_seconds, 3),
        "sum_phase_seconds": round(serial_sum, 3),
        "overlap_saved_s": round(max(0.0, serial_sum - wall_seconds), 3),
    }


def phase_histograms(runs: list[dict]) -> dict:
    """Aggregate each run's journal-derived phase durations into a
    per-phase summary: the BENCH artifact reports distributions, not one
    run's totals (a single noisy drain should read as tail, not truth)."""
    merged: dict[str, list[float]] = {}
    for run in runs:
        for phase, secs in (run.get("phase_durations") or {}).items():
            merged.setdefault(phase, []).extend(secs)
    out = {}
    for phase in phase_names() + ("reset.chip",):
        vals = sorted(merged.get(phase, ()))
        if not vals:
            continue
        out[phase] = {
            "count": len(vals),
            "min": round(vals[0], 3),
            "p50": round(vals[(len(vals) - 1) // 2], 3),
            "max": round(vals[-1], 3),
            "sum": round(sum(vals), 3),
        }
    return out


def make_bench_kube(node_names: list[str], pod_delete_delay_s: float = 0.0):
    """Fake apiserver with one pod per drain component per node and the
    emulated operator controller (tpu_cc_manager/drain/sim.py — one
    implementation shared with the serving harness so the drain-protocol
    emulation cannot diverge between the scenarios and artifacts)."""
    from tpu_cc_manager.drain.sim import add_drainable_node
    from tpu_cc_manager.kubeclient.fake import FakeKube

    kube = FakeKube()
    for name in node_names:
        add_drainable_node(
            kube, name, NS, pod_delete_delay_s=pod_delete_delay_s,
        )
    return kube


def run_scenario(
    tpu_usable: bool,
    reset_latency_s=0.0,
    boot_latency_s=0.0,
    pod_delete_delay_s: float = 0.0,
    reset_parallelism: int | None = None,
) -> dict:
    """One drain→CC-on→ready pass through the real pipeline; returns the
    measurement plus the smoke detail."""
    from tpu_cc_manager.ccmanager.manager import CCManager
    from tpu_cc_manager.kubeclient.api import node_labels
    from tpu_cc_manager.labels import CC_MODE_STATE_LABEL
    from tpu_cc_manager.obs.journal import Journal
    from tpu_cc_manager.tpudev.fake import FakeTpuBackend
    from tpu_cc_manager.utils.metrics import MetricsRegistry

    node, ns = "bench-node-0", NS
    kube = make_bench_kube([node], pod_delete_delay_s)

    backend_used = {"backend": "unknown"}
    smoke_detail: dict = {}

    def smoke_runner(workload: str) -> dict:
        from tpu_cc_manager.smoke.runner import SmokeError

        try:
            result = _smoke_subprocess(
                workload, timeout_s=240.0, force_cpu=not tpu_usable
            )
        except SmokeError:
            # Chip passed preflight but failed mid-run: fall back to CPU so
            # the bench still measures the pipeline end-to-end.
            result = _smoke_subprocess(workload, timeout_s=240.0, force_cpu=True)
        backend_used["backend"] = result.get("backend", "?")
        smoke_detail.update(result)
        return result

    class _BenchWarmup:
        """The manager's warmup handle, bench-flavored: same real
        subprocess + dispatch gate (smoke/runner.py SmokeWarmup), plus
        the bench's CPU fallback and result capture. This is how the
        realistic scenario MODELS the wait_ready∥COMPILE overlap — by
        actually doing it: the smoke child compiles while the fake
        backend's 20 s boot runs, and only the post-release dispatch
        lands in the measured smoke phase."""

        def __init__(self, workload: str) -> None:
            from tpu_cc_manager.smoke.runner import SmokeWarmup

            self._workload = workload
            self._inner = SmokeWarmup(
                workload, timeout_s=240.0, force_cpu=not tpu_usable,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            )

        def release(self) -> None:
            self._inner.release()

        def cancel(self, reason: str = "") -> None:
            self._inner.cancel(reason)

        def died_during_warmup(self) -> bool:
            return self._inner.died_during_warmup()

        def release_and_result(self) -> dict:
            from tpu_cc_manager.smoke.runner import SmokeError

            try:
                result = self._inner.release_and_result()
            except SmokeError:
                # Same CPU fallback as the synchronous path: the chip
                # failed mid-run, the bench still measures end-to-end.
                result = _smoke_subprocess(
                    self._workload, timeout_s=240.0, force_cpu=True
                )
            backend_used["backend"] = result.get("backend", "?")
            smoke_detail.update(result)
            return result

    registry = MetricsRegistry()
    # Per-scenario journal (file sink off): the bench reads the span
    # stream back to report per-phase distributions, not just one run's
    # totals, and must not inherit a CC_TRACE_FILE from the environment.
    journal = Journal(trace_file="")
    backend = FakeTpuBackend(
        num_chips=4,
        accelerator_type="v5p-8",
        reset_latency_s=reset_latency_s,
        boot_latency_s=boot_latency_s,
        reset_parallelism_override=reset_parallelism,
    )
    mgr = CCManager(
        api=kube,
        backend=backend,
        node_name=node,
        operator_namespace=ns,
        evict_components=True,
        smoke_workload="matmul",
        smoke_runner=smoke_runner,
        # Boot-wait∥COMPILE overlap: the warmup factory launches the REAL
        # smoke subprocess gated at its dispatch boundary while the fake
        # backend's boot latency runs (CC_SMOKE_WARMUP path in the
        # manager); smoke_runner stays as the spawn-failure fallback.
        smoke_warmup_factory=_BenchWarmup,
        eviction_poll_interval_s=0.1,
        metrics=registry,
        journal=journal,
    )

    t0 = time.perf_counter()
    ok = mgr.set_cc_mode("on")
    dt = time.perf_counter() - t0

    state = node_labels(kube.get_node(node)).get(CC_MODE_STATE_LABEL)
    m = registry.last()
    durations = journal.phase_durations(phase_names() + ("reset.chip",))
    # The warmup's pre-release compile span ran hidden inside wait_ready:
    # add it to the serialized-equivalent sum (a serial pipeline would
    # have paid it inside the smoke phase), never double-counting — the
    # measured smoke phase only contains post-release work.
    warmup_overlap = smoke_detail.get("warmup_overlap_s") or 0.0
    return {
        "seconds": round(dt, 2),
        "ok": bool(ok and state == "on"),
        "phases": {p.name: round(p.seconds, 3) for p in (m.phases if m else [])},
        "trace_id": m.trace_id if m else None,
        "phase_durations": durations,
        # Wall-vs-serialized-sum accounting (pipelined transitions): the
        # per-phase numbers above no longer sum to the wall time once
        # phases overlap, so the saving is reported explicitly.
        **phase_accounting(durations, dt, smoke_compile_overlap_s=warmup_overlap),
        "smoke_warmup": {
            "compile_s": smoke_detail.get("warmup_compile_s"),
            "overlap_s": smoke_detail.get("warmup_overlap_s"),
            "dispatch_s": smoke_detail.get("warmup_dispatch_s"),
        },
        "smoke": smoke_detail,
        "backend": backend_used["backend"],
    }


def run_spare_prestage_scenario(
    tpu_usable: bool,
    reset_latency_s=None,
    boot_latency_s: float = 20.0,
    pod_delete_delay_s: float = 3.0,
) -> dict:
    """BENCH_r08: the zero-bounce spare. A 2-node pool of REAL agents
    (realistic device latencies, same 30 s reset / 20 s boot model as
    the headline scenario) driven by the REAL rolling orchestrator with
    ``surge=1, prestage=True``: the spare is armed (surge taint +
    prestage annotation), runs its FULL journaled flip + compile warmup
    ahead of the wave and HOLDS; its flip window then converges in
    ~drain+readmit time while the second node pays the full path in the
    SAME run — the internal control the artifact compares against.

    The claim the JSON gates on: the pre-staged spare's effective flip
    wall (desired write → converged, orchestrator-measured) is at most
    the drain + readmit cost of its own prestage transition
    (journal-measured), and strictly below the full path its pool-mate
    paid."""
    import tempfile
    import threading as _threading

    from tpu_cc_manager.ccmanager.manager import CCManager
    from tpu_cc_manager.ccmanager.rolling import RollingReconfigurator
    from tpu_cc_manager.kubeclient.api import node_labels
    from tpu_cc_manager.labels import CC_MODE_STATE_LABEL
    from tpu_cc_manager.obs import flight as flight_mod
    from tpu_cc_manager.obs.journal import Journal
    from tpu_cc_manager.tpudev.fake import FakeTpuBackend
    from tpu_cc_manager.utils import retry as retry_mod
    from tpu_cc_manager.utils.metrics import MetricsRegistry

    if reset_latency_s is None:
        reset_latency_s = [7.5, 7.5, 7.5, 7.5]
    from tpu_cc_manager.drain.sim import add_drainable_node
    from tpu_cc_manager.kubeclient.fake import FakeKube

    kube = FakeKube()
    names = ["bench-spare-0", "bench-spare-1"]
    journals: dict[str, Journal] = {}
    stop = _threading.Event()
    threads = []
    for i, name in enumerate(names):
        add_drainable_node(
            kube, name, NS, pod_delete_delay_s=pod_delete_delay_s,
            extra_labels={"pool": "bench-spare"},
        )
        journals[name] = Journal(trace_file="")
        backend = FakeTpuBackend(
            num_chips=4,
            accelerator_type="v5p-8",
            slice_id=f"bench-spare-slice-{i}",
            reset_latency_s=reset_latency_s,
            boot_latency_s=boot_latency_s,
            reset_parallelism_override=4,
        )
        mgr = CCManager(
            api=kube,
            backend=backend,
            node_name=name,
            default_mode="off",
            operator_namespace=NS,
            evict_components=True,
            smoke_workload="matmul",
            smoke_runner=lambda w: _smoke_subprocess(
                w, timeout_s=240.0, force_cpu=not tpu_usable
            ),
            eviction_poll_interval_s=0.1,
            metrics=MetricsRegistry(),
            journal=journals[name],
            watch_timeout_s=1,
            reconnect_delay_s=0.0,
        )
        t = _threading.Thread(
            target=mgr.watch_and_apply, args=(stop,), daemon=True,
            name=f"bench-spare-agent-{name}",
        )
        threads.append(t)
    for t in threads:
        t.start()

    def settled() -> bool:
        return all(
            node_labels(kube.get_node(n)).get(CC_MODE_STATE_LABEL) == "off"
            for n in names
        )

    result: dict = {"ok": False}
    try:
        if not retry_mod.poll_until(settled, 60.0, 0.1):
            result["error"] = "agents never settled at mode off"
            return result
        flight_path = tempfile.mktemp(
            prefix="tpu-cc-bench-spare-", suffix=".jsonl"
        )
        flight = flight_mod.FlightRecorder(flight_path)
        roller = RollingReconfigurator(
            kube, "pool=bench-spare",
            max_unavailable=1,
            node_timeout_s=600.0,
            poll_interval_s=0.05,
            surge=1,
            prestage=True,
            flight=flight,
            metrics=MetricsRegistry(),
        )
        t0 = time.perf_counter()
        rres = roller.rollout("on")
        rollout_wall = time.perf_counter() - t0
        events, _torn = flight_mod.read_events(flight_path)
        prestaged_events = [
            e for e in events if e["event"] == flight_mod.EVENT_SPARE_PRESTAGED
        ]
        spare = prestaged_events[0]["node"] if prestaged_events else None
        surge_windows = [
            e for e in events
            if e["event"] == flight_mod.EVENT_WINDOW_CLOSE
            and e.get("wave") == "surge"
        ]
        full_windows = [
            e for e in events
            if e["event"] == flight_mod.EVENT_WINDOW_CLOSE
            and e.get("wave") == 0
        ]
        effective = surge_windows[0].get("seconds") if surge_windows else None
        full_path = full_windows[0].get("seconds") if full_windows else None
        prestage_wall = (
            prestaged_events[0].get("seconds") if prestaged_events else None
        )
        # The bar: what the spare's OWN prestage transition spent on the
        # two phases a pre-staged flip cannot skip in principle — the
        # drain bracket and re-admission. Everything else (stage, reset,
        # boot, verify, smoke) ran ahead of the wave.
        drain_s = readmit_s = None
        if spare is not None:
            durs = journals[spare].phase_durations(("drain", "readmit"))
            drain_s = round(sum(durs.get("drain", ())), 3)
            readmit_s = round(sum(durs.get("readmit", ())), 3)
        bar = (
            round(drain_s + readmit_s, 3)
            if drain_s is not None and readmit_s is not None else None
        )
        states = {
            n: node_labels(kube.get_node(n)).get(CC_MODE_STATE_LABEL)
            for n in names
        }
        result = {
            "rollout_ok": bool(rres.ok),
            "rollout_wall_s": round(rollout_wall, 2),
            "spare": spare,
            "prestage_wall_s": prestage_wall,
            "effective_flip_wall_s": effective,
            "full_path_wall_s": full_path,
            "drain_s": drain_s,
            "readmit_s": readmit_s,
            "bar_drain_plus_readmit_s": bar,
            "states": states,
            "surged": rres.surged,
            "ok": bool(
                rres.ok
                and spare is not None
                and effective is not None
                and bar is not None
                and effective <= bar
                and full_path is not None
                and effective < full_path
                and all(s == "on" for s in states.values())
            ),
        }
        return result
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=15)


def run_multihost_scenario() -> dict:
    """Two agents of one 2-host slice transition to mode 'slice' through
    the cross-host commit barrier (ccmanager/slicecoord.py) — the
    fabric-atomicity evidence: wall time for the whole slice, plus each
    host's time spent waiting at the barrier."""
    from tpu_cc_manager.ccmanager.manager import CCManager
    from tpu_cc_manager.kubeclient.api import node_labels
    from tpu_cc_manager.labels import CC_MODE_STATE_LABEL
    from tpu_cc_manager.obs.journal import Journal
    from tpu_cc_manager.tpudev.fake import FakeTpuBackend
    from tpu_cc_manager.utils.metrics import MetricsRegistry

    ns = NS
    names = [f"bench-mh-{i}" for i in range(2)]
    kube = make_bench_kube(names)

    managers = []
    for i, name in enumerate(names):
        backend = FakeTpuBackend(
            num_chips=4, accelerator_type="v5p-32",
            num_hosts=2, host_index=i, slice_id="bench-slice",
        )
        managers.append(CCManager(
            api=kube, backend=backend, node_name=name,
            operator_namespace=ns, evict_components=True,
            smoke_workload="none", metrics=MetricsRegistry(),
            # Bench spans must not land in an operator's CC_TRACE_FILE.
            journal=Journal(trace_file=""),
            eviction_poll_interval_s=0.05,
            slice_barrier_poll_interval_s=0.02,
        ))

    results = {}
    t0 = time.perf_counter()
    threads = [
        threading.Thread(
            target=lambda i=i, m=m: results.update({i: m.set_cc_mode("slice")}),
            daemon=True,  # a wedged reconcile must not hold the bench open
        )
        for i, m in enumerate(managers)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    dt = time.perf_counter() - t0
    timed_out = any(t.is_alive() for t in threads)

    states = [
        node_labels(kube.get_node(n)).get(CC_MODE_STATE_LABEL) for n in names
    ]
    barrier_waits = [
        round(m.metrics.last().phase_seconds("barrier"), 3)
        if m.metrics.last() else None
        for m in managers
    ]
    return {
        "seconds": round(dt, 2),
        "ok": (
            not timed_out
            and all(results.get(i) for i in range(2))
            and states == ["slice"] * 2
        ),
        "barrier_wait_s": barrier_waits,
    }


def run_handshake_scenario(checkpoint_s: float = 0.5) -> dict:
    """One drain with a registered training job that checkpoints before
    the pause (drain/handshake.py): measures what the workload handshake
    adds to the drain window (ack wait = job checkpoint time + one poll),
    and asserts the ordering the feature exists for — checkpoint strictly
    before any component pause."""
    from tpu_cc_manager.ccmanager.manager import CCManager
    from tpu_cc_manager.drain import handshake
    from tpu_cc_manager.drain.pause import is_paused
    from tpu_cc_manager.kubeclient.api import node_labels
    from tpu_cc_manager.labels import CC_MODE_STATE_LABEL, DRAIN_COMPONENT_LABELS
    from tpu_cc_manager.obs.journal import Journal
    from tpu_cc_manager.tpudev.fake import FakeTpuBackend
    from tpu_cc_manager.utils.metrics import MetricsRegistry

    node = "bench-hs-0"
    kube = make_bench_kube([node])
    events: list[str] = []

    def reactor(name, patched):
        # ANY component pausing marks the drain as begun — the invariant is
        # "checkpoint before any pause", not before one specific component.
        labels = node_labels(patched)
        if any(is_paused(labels.get(k)) for k in DRAIN_COMPONENT_LABELS):
            if "paused" not in events:
                events.append("paused")

    kube.add_patch_reactor(reactor)

    def on_drain():
        time.sleep(checkpoint_s)  # the simulated checkpoint write
        events.append("checkpointed")

    sub = handshake.DrainSubscriber(
        kube, node, "bench-train", on_drain=on_drain, poll_interval_s=0.05
    )
    # Register synchronously BEFORE the reconcile starts: the poll thread's
    # own (idempotent) registration could otherwise land after
    # request_drain snapshots the subscriber set, skipping the ack wait.
    sub.register()
    sub.start()
    mgr = CCManager(
        api=kube,
        backend=FakeTpuBackend(),
        node_name=node,
        operator_namespace=NS,
        evict_components=True,
        smoke_workload="none",
        metrics=MetricsRegistry(),
        # Bench spans must not land in an operator's CC_TRACE_FILE.
        journal=Journal(trace_file=""),
        eviction_poll_interval_s=0.05,
        drain_ack_timeout_s=30,
    )
    t0 = time.perf_counter()
    ok = mgr.set_cc_mode("on")
    dt = time.perf_counter() - t0
    sub.stop()

    state = node_labels(kube.get_node(node)).get(CC_MODE_STATE_LABEL)
    ordered = events[:2] == ["checkpointed", "paused"]
    return {
        "seconds": round(dt, 2),
        "checkpoint_s": checkpoint_s,
        "ok": bool(ok and state == "on" and ordered),
        "checkpoint_before_pause": ordered,
    }


def measure_smoke_cache(
    tpu_usable: bool, workload: str = "matmul", timeout_s: float = 300.0,
) -> dict:
    """Cold-vs-warm smoke across a simulated CC bounce: prove the
    persistent XLA compilation cache (utils/compilation_cache.py) instead
    of claiming it (VERDICT weak #2).

    Cold = a fresh, empty cache directory; warm = the populated directory
    — both in a FRESH subprocess, which is exactly what a CC bounce does
    to the verify phase (the runtime restart kills the process; only the
    disk cache persists). The delta between the two runs IS the compile
    time the cache holds down."""
    import shutil
    import tempfile

    cache_dir = tempfile.mkdtemp(prefix="tpu-cc-smoke-cache-")
    extra_env = {
        # Both knobs: enable() honors an existing JAX_COMPILATION_CACHE_DIR
        # outright, and TPU_CC_CACHE_DIR covers any path that re-derives
        # candidates.
        "JAX_COMPILATION_CACHE_DIR": cache_dir,
        "TPU_CC_CACHE_DIR": cache_dir,
        # This stage MEASURES the cache, so it must be on in the child
        # sandbox regardless of the outer environment: clear the opt-out
        # and pin the cache-everything thresholds an inherited env could
        # otherwise override (a sub-second CPU compile writing no entry
        # would read as a cache failure and fail the whole bench).
        "TPU_CC_NO_COMPILATION_CACHE": "",
        "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS": "0",
        "JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES": "-1",
    }
    result = {
        "workload": workload,
        "smoke_cold_s": None,
        "smoke_warm_s": None,
        "cache_entries": 0,
        "backend": None,
        "ok": False,
    }
    try:
        t0 = time.perf_counter()
        cold = _smoke_subprocess(
            workload, timeout_s=timeout_s, force_cpu=not tpu_usable,
            extra_env=extra_env,
        )
        result["smoke_cold_s"] = round(time.perf_counter() - t0, 3)
        result["cache_entries"] = len(os.listdir(cache_dir))
        t0 = time.perf_counter()
        warm = _smoke_subprocess(
            workload, timeout_s=timeout_s, force_cpu=not tpu_usable,
            extra_env=extra_env,
        )
        result["smoke_warm_s"] = round(time.perf_counter() - t0, 3)
        result["backend"] = warm.get("backend", cold.get("backend"))
        result["ok"] = bool(
            cold.get("ok") and warm.get("ok") and result["cache_entries"] > 0
        )
        if result["smoke_warm_s"]:
            result["warm_speedup"] = round(
                result["smoke_cold_s"] / result["smoke_warm_s"], 3
            )
    except Exception as e:  # noqa: BLE001 - the bench must still emit its line
        result["error"] = str(e)[:256]
    finally:
        shutil.rmtree(cache_dir, ignore_errors=True)
    return result


def main() -> int:
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import logging

    logging.basicConfig(level=logging.WARNING)  # keep stdout to one JSON line

    tpu_usable = _tpu_preflight()

    control = run_scenario(tpu_usable)
    # The realistic scenario is the headline; on this rig the smoke's chip
    # is reached through a shared remote tunnel whose dispatch latency is
    # erratic (observed 12–75 s wall for identical work at identical
    # chip-side throughput — a rig artifact production TPU VMs, with local
    # libtpu, don't have). Median of N runs absorbs that noise honestly:
    # every raw value is reported alongside.
    runs = max(1, int(os.environ.get("CC_BENCH_REALISTIC_RUNS", "3")))
    realistic_runs = [
        run_scenario(
            tpu_usable,
            # Same 30 s of total reset work as BENCH_r01–r05 (the
            # serialized-equivalent sum is unchanged), now modeled
            # per-chip — 7.5 s × 4 chips — so the bounded-pool parallel
            # reset (tpudev, CC_RESET_PARALLELISM) is measurable: the
            # pipeline pays one chip's reset of wall time, the old serial
            # walk paid all four.
            reset_latency_s=[7.5, 7.5, 7.5, 7.5],
            boot_latency_s=20.0,
            pod_delete_delay_s=3.0,
            reset_parallelism=4,
        )
        for _ in range(runs)
    ]
    realistic = sorted(realistic_runs, key=lambda r: r["seconds"])[
        (len(realistic_runs) - 1) // 2
    ]
    multihost = run_multihost_scenario()
    handshake = run_handshake_scenario()
    # Compilation-cache proof: cold vs warm smoke across a simulated CC
    # bounce (fresh process each run; only the disk cache persists).
    smoke_cache = measure_smoke_cache(tpu_usable)

    dt = realistic["seconds"]
    # Median chip-side metrics across all runs; rationale in the helper.
    best_backend, smoke, timed = select_headline_smoke(
        [control["smoke"]] + [r["smoke"] for r in realistic_runs],
        control_backend=control["backend"],
    )
    # The smoke result only self-reports a generation when it ran ON the
    # chip; a CPU-fallback smoke on a TPU host still knows what chip the
    # node carries (env: PALLAS_AXON_TPU_GEN / TPU_ACCELERATOR_TYPE, else
    # device_kind) — per-generation result keying (ROADMAP 5b) needs the
    # field populated either way.
    from tpu_cc_manager.utils.tpu_info import tpu_generation

    chip_generation = smoke.get("generation") or tpu_generation()
    result = {
        "metric": "node_drain_cc_on_ready_sec",
        # Headline is the REALISTIC scenario (simulated-real device
        # latencies: 30 s reset, 20 s boot, 3 s pod termination) — the
        # honest number for the <90 s target. The zero-device-latency
        # control run rides along as `control`.
        "value": dt,
        "unit": "s",
        "vs_baseline": round(90.0 / dt, 2) if dt > 0 else 0.0,
        "ok": bool(control["ok"] and all(r["ok"] for r in realistic_runs)),
        "smoke_backend": best_backend,
        "chip_generation": chip_generation,
        "smoke_tflops": smoke.get("tflops"),
        "smoke_mfu": smoke.get("mfu"),
        # Raw chip-side values behind the median above, one per run that
        # hit `smoke_backend` — the spread is the tunnel's, not the chip's.
        "smoke_tflops_runs": [s["tflops"] for s in timed],
        "phases": realistic["phases"],
        # Pipelined-transition accounting (the phases above overlap, so
        # they no longer sum to the wall time): wall vs what the serial
        # pipeline would have paid, and the saving.
        "wall_seconds": realistic["wall_seconds"],
        "sum_phase_seconds": realistic["sum_phase_seconds"],
        "overlap_saved_s": realistic["overlap_saved_s"],
        # Boot-wait∥COMPILE warmup (smoke/runner.py dispatch gate): how
        # much of the smoke's compile span the wait_ready boot absorbed.
        "smoke_warmup": realistic["smoke_warmup"],
        # Compilation-cache proof (VERDICT weak #2): cold vs warm smoke
        # wall time across a simulated CC bounce, from measurement — the
        # delta is the compile time the persistent cache holds down.
        "smoke_cold_s": smoke_cache["smoke_cold_s"],
        "smoke_warm_s": smoke_cache["smoke_warm_s"],
        "smoke_cache": smoke_cache,
        # Journal-derived per-phase distributions across every realistic
        # run (obs/journal.py): which phase owns the tail, not just the
        # median run's totals.
        "phase_histograms": phase_histograms(realistic_runs),
        "under_target": dt < 90.0,
        # Control-plane-only overhead (zero device latencies): what this
        # framework itself costs, separated from simulated device time.
        "control": {
            "seconds": control["seconds"],
            "phases": control["phases"],
        },
        # Kept for artifact-shape continuity with BENCH_r01–r03; the
        # headline is the median run, raw values disclose the spread.
        "realistic": {
            "seconds": realistic["seconds"],
            "under_target": realistic["seconds"] < 90.0,
            "phases": realistic["phases"],
            "runs_seconds": [r["seconds"] for r in realistic_runs],
            "runs_overlap_saved_s": [
                r["overlap_saved_s"] for r in realistic_runs
            ],
        },
        # Fabric atomicity evidence: both hosts of a 2-host slice through
        # the cross-host commit barrier (ccmanager/slicecoord.py).
        "multihost_slice": multihost,
        # Workload-handshake cost: a registered training job checkpoints
        # (0.5 s simulated) strictly before any component pause; the
        # scenario's wall time bounds what the handshake adds to a drain.
        "workload_handshake": handshake,
    }
    result["ok"] = bool(
        result["ok"] and multihost["ok"] and handshake["ok"]
        and smoke_cache["ok"]
    )
    print(json.dumps(result))
    return 0 if result["ok"] and result["realistic"]["under_target"] else 1


def spare_main(out: str | None) -> int:
    """BENCH_r08 entry (`python bench.py --spare [--out FILE]`): one
    JSON line for the zero-bounce spare scenario, ok-gated on the
    pre-staged spare's effective flip wall landing at or under its own
    drain+readmit cost AND strictly below BENCH_r07's full-path wall."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import logging

    logging.basicConfig(level=logging.WARNING)  # stdout carries ONE line

    tpu_usable = _tpu_preflight()
    spare = run_spare_prestage_scenario(tpu_usable)
    # BENCH_r07's measured full-path per-node wall: the pre-staged
    # spare's effective flip must land strictly below it (it lands ~two
    # orders under — the whole flip ran ahead of the wave).
    reference = 31.45
    value = spare.get("effective_flip_wall_s")
    result = {
        "metric": "spare_prestage_flip_sec",
        "value": value,
        "unit": "s",
        "full_path_reference_s": reference,
        **spare,
    }
    result["ok"] = bool(
        result["ok"] and value is not None and value < reference
    )
    line = json.dumps(result)
    print(line)
    if out:
        with open(out, "w", encoding="utf-8") as f:
            f.write(line + "\n")
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    if "--spare" in sys.argv:
        _out = None
        if "--out" in sys.argv:
            _out = sys.argv[sys.argv.index("--out") + 1]
        sys.exit(spare_main(_out))
    sys.exit(main())
