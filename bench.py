"""End-to-end benchmark: per-node drain → CC-on → ready latency.

North-star metric (BASELINE.md): < 90 s per-node drain→CC-on→ready. The
reference publishes no numbers (SURVEY.md §6); 90 s is the target from
BASELINE.json and ``vs_baseline`` reports how many times under target we
land (value 9 s → vs_baseline 10.0).

Two scenarios run, both through the REAL reconcile pipeline (CCManager)
against the in-memory apiserver fake and the fake TPU device layer — pause
labels, pod-drain polling with an emulated operator controller,
stage/reset/wait, attestation fetch + verification, and the REAL JAX matmul
smoke workload in a subprocess on whatever accelerator this machine has
(the driver runs this on one real TPU chip):

- **control** (the headline ``value``): zero device latencies — measures the
  control plane's own overhead plus the end-to-end JAX verification, the
  part this framework is responsible for.
- **realistic**: the fake device is configured with defensible real-world
  latencies (30 s runtime reset, 20 s boot — the order of a TPU runtime
  restart — and a 3 s pod-termination delay per the operator controller),
  so the <90 s claim is made against simulated-real device time, not
  zero-cost fakes.

The result is self-describing: smoke backend, chip generation, and MFU ride
along so the throughput number carries its own denominator.

Prints exactly one JSON line.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time


def _tpu_preflight(timeout_s: float = 90.0) -> bool:
    """Probe the accelerator OUTSIDE the timed region.

    A wedged TPU transport hangs dispatches without erroring; discovering
    that inside the timed reconcile would charge the hang + CPU retry to
    the drain→ready metric. Probe in a child process first and pin the
    smoke to CPU when the chip isn't usable.
    """
    probe = (
        "import jax, jax.numpy as jnp;"
        "print(float(jax.jit(lambda x: (x @ x).sum())(jnp.ones((128, 128)))))"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", probe], capture_output=True,
            timeout=timeout_s, text=True,
        )
    except subprocess.TimeoutExpired:
        return False
    return proc.returncode == 0


def _smoke_subprocess(workload: str, timeout_s: float, force_cpu: bool) -> dict:
    # Shared subprocess-smoke contract (tpu_cc_manager/smoke/runner.py);
    # imported lazily so the module parses before sys.path setup.
    from tpu_cc_manager.smoke.runner import run_workload_subprocess

    return run_workload_subprocess(
        workload, timeout_s=timeout_s, force_cpu=force_cpu,
        cwd=os.path.dirname(os.path.abspath(__file__)),
    )


def run_scenario(
    tpu_usable: bool,
    reset_latency_s: float = 0.0,
    boot_latency_s: float = 0.0,
    pod_delete_delay_s: float = 0.0,
) -> dict:
    """One drain→CC-on→ready pass through the real pipeline; returns the
    measurement plus the smoke detail."""
    from tpu_cc_manager.ccmanager.manager import CCManager
    from tpu_cc_manager.drain.pause import is_paused
    from tpu_cc_manager.kubeclient.api import node_labels
    from tpu_cc_manager.kubeclient.fake import FakeKube
    from tpu_cc_manager.labels import (
        CC_MODE_STATE_LABEL,
        DRAIN_COMPONENT_LABELS,
    )
    from tpu_cc_manager.tpudev.fake import FakeTpuBackend
    from tpu_cc_manager.utils.metrics import MetricsRegistry

    node, ns = "bench-node-0", "tpu-operator"
    kube = FakeKube()
    labels = {key: "true" for key in DRAIN_COMPONENT_LABELS}
    kube.add_node(node, labels)
    for key, app in DRAIN_COMPONENT_LABELS.items():
        kube.add_pod(ns, f"{app}-pod", node, labels={"app": app})

    # Emulated operator controller: deletes a component's pods when its
    # deploy label flips to paused (the external behavior the protocol
    # relies on; SURVEY.md §5) — after the configured termination delay in
    # the realistic scenario (pods have grace periods; deletion is not
    # instantaneous on a real cluster).
    def reactor(name, patched):
        for key, app in DRAIN_COMPONENT_LABELS.items():
            if is_paused(node_labels(patched).get(key)):
                if pod_delete_delay_s > 0:
                    threading.Timer(
                        pod_delete_delay_s,
                        kube.delete_pods_matching, (ns, f"app={app}"),
                    ).start()
                else:
                    kube.delete_pods_matching(ns, f"app={app}")

    kube.add_patch_reactor(reactor)

    backend_used = {"backend": "unknown"}
    smoke_detail: dict = {}

    def smoke_runner(workload: str) -> dict:
        from tpu_cc_manager.smoke.runner import SmokeError

        try:
            result = _smoke_subprocess(
                workload, timeout_s=240.0, force_cpu=not tpu_usable
            )
        except SmokeError:
            # Chip passed preflight but failed mid-run: fall back to CPU so
            # the bench still measures the pipeline end-to-end.
            result = _smoke_subprocess(workload, timeout_s=240.0, force_cpu=True)
        backend_used["backend"] = result.get("backend", "?")
        smoke_detail.update(result)
        return result

    registry = MetricsRegistry()
    backend = FakeTpuBackend(
        num_chips=4,
        accelerator_type="v5p-8",
        reset_latency_s=reset_latency_s,
        boot_latency_s=boot_latency_s,
    )
    mgr = CCManager(
        api=kube,
        backend=backend,
        node_name=node,
        operator_namespace=ns,
        evict_components=True,
        smoke_workload="matmul",
        smoke_runner=smoke_runner,
        eviction_poll_interval_s=0.1,
        metrics=registry,
    )

    t0 = time.perf_counter()
    ok = mgr.set_cc_mode("on")
    dt = time.perf_counter() - t0

    state = node_labels(kube.get_node(node)).get(CC_MODE_STATE_LABEL)
    m = registry.last()
    return {
        "seconds": round(dt, 2),
        "ok": bool(ok and state == "on"),
        "phases": {p.name: round(p.seconds, 3) for p in (m.phases if m else [])},
        "smoke": smoke_detail,
        "backend": backend_used["backend"],
    }


def main() -> int:
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import logging

    logging.basicConfig(level=logging.WARNING)  # keep stdout to one JSON line

    tpu_usable = _tpu_preflight()

    control = run_scenario(tpu_usable)
    realistic = run_scenario(
        tpu_usable,
        reset_latency_s=30.0,
        boot_latency_s=20.0,
        pod_delete_delay_s=3.0,
    )

    dt = control["seconds"]
    smoke = control["smoke"]
    result = {
        "metric": "node_drain_cc_on_ready_sec",
        "value": dt,
        "unit": "s",
        "vs_baseline": round(90.0 / dt, 2) if dt > 0 else 0.0,
        "ok": bool(control["ok"] and realistic["ok"]),
        "smoke_backend": control["backend"],
        "chip_generation": smoke.get("generation"),
        "smoke_tflops": smoke.get("tflops"),
        "smoke_mfu": smoke.get("mfu"),
        "phases": control["phases"],
        # The <90 s claim against simulated-real device time (30 s reset,
        # 20 s boot, 3 s pod termination), not zero-cost fakes.
        "realistic": {
            "seconds": realistic["seconds"],
            "under_target": realistic["seconds"] < 90.0,
            "phases": realistic["phases"],
        },
    }
    print(json.dumps(result))
    return 0 if result["ok"] and result["realistic"]["under_target"] else 1


if __name__ == "__main__":
    sys.exit(main())
