/* rmutil: a tiny static `rm` for the distroless agent image.
 *
 * Why this exists (same reason as the reference's rmsrc/rm.c, SURVEY.md §2
 * #11): the shipped container is distroless — no shell, no coreutils — but
 * the DaemonSet's preStop hook must delete the readiness file
 * (/run/tpu/validations/.tpu-cc-manager-ctr-ready) so the operator's
 * validation framework notices the agent is gone. A ~100-line static binary
 * is cheaper and smaller than pulling busybox into the image.
 *
 * Design (deliberately not the reference's nftw() walk): recursion is done
 * with openat()/fdopendir()/unlinkat() relative to directory fds, so it
 * needs no PATH_MAX buffers, is immune to path-length limits, and cannot be
 * redirected by a concurrent rename of an ancestor directory.
 *
 * Usage: rm [-r] [-f] [--] PATH...
 *   -r  recurse into directories
 *   -f  ignore missing paths and all errors (exit 0)
 */

#include <dirent.h>
#include <errno.h>
#include <fcntl.h>
#include <stdio.h>
#include <string.h>
#include <sys/stat.h>
#include <unistd.h>

static int opt_recursive = 0;
static int opt_force = 0;
static int exit_status = 0;

static void complain(const char *path, const char *what) {
    if (!opt_force) {
        fprintf(stderr, "rm: %s: %s: %s\n", what, path, strerror(errno));
        exit_status = 1;
    }
}

/* Remove everything inside the directory open at fd (consumes fd). */
static int clear_dir(int fd, const char *label) {
    DIR *dir = fdopendir(fd);
    if (!dir) {
        close(fd);
        return -1;
    }
    int ok = 0;
    struct dirent *ent;
    while ((ent = readdir(dir)) != NULL) {
        if (strcmp(ent->d_name, ".") == 0 || strcmp(ent->d_name, "..") == 0)
            continue;
        if (unlinkat(dirfd(dir), ent->d_name, 0) == 0)
            continue;
        if (errno != EISDIR && errno != EPERM) {
            complain(ent->d_name, "cannot remove");
            ok = -1;
            continue;
        }
        /* Probably a directory: descend and retry. */
        int sub = openat(dirfd(dir), ent->d_name,
                         O_RDONLY | O_DIRECTORY | O_NOFOLLOW | O_CLOEXEC);
        if (sub < 0 || clear_dir(sub, ent->d_name) != 0) {
            complain(ent->d_name, "cannot descend into");
            ok = -1;
            continue;
        }
        if (unlinkat(dirfd(dir), ent->d_name, AT_REMOVEDIR) != 0) {
            complain(ent->d_name, "cannot rmdir");
            ok = -1;
        }
        /* readdir() state can be stale after deletions; restart the scan so
         * nothing is skipped. */
        rewinddir(dir);
    }
    (void)label;
    closedir(dir);
    return ok;
}

static void remove_path(const char *path) {
    if (unlink(path) == 0)
        return;
    if (errno == ENOENT) {
        if (!opt_force) {
            fprintf(stderr, "rm: no such file: %s\n", path);
            exit_status = 1;
        }
        return;
    }
    if (errno != EISDIR && errno != EPERM) {
        complain(path, "cannot remove");
        return;
    }
    if (!opt_recursive) {
        errno = EISDIR;
        complain(path, "is a directory (need -r)");
        return;
    }
    int fd = open(path, O_RDONLY | O_DIRECTORY | O_NOFOLLOW | O_CLOEXEC);
    if (fd < 0) {
        complain(path, "cannot open");
        return;
    }
    if (clear_dir(fd, path) != 0 && !opt_force)
        return;
    if (rmdir(path) != 0)
        complain(path, "cannot rmdir");
}

int main(int argc, char **argv) {
    int i = 1;
    for (; i < argc && argv[i][0] == '-' && argv[i][1] != '\0'; i++) {
        if (strcmp(argv[i], "--") == 0) {
            i++;
            break;
        }
        for (const char *f = argv[i] + 1; *f; f++) {
            switch (*f) {
            case 'r':
            case 'R':
                opt_recursive = 1;
                break;
            case 'f':
                opt_force = 1;
                break;
            default:
                fprintf(stderr, "rm: unknown flag -%c\n", *f);
                return 2;
            }
        }
    }
    if (i >= argc) {
        if (opt_force)
            return 0;
        fprintf(stderr, "usage: rm [-r] [-f] [--] PATH...\n");
        return 2;
    }
    for (; i < argc; i++)
        remove_path(argv[i]);
    return opt_force ? 0 : exit_status;
}
