"""CC-on vs CC-off A/B harness for the ≤3% MFU-loss north-star.

BASELINE.md's second target — "≤ 3 % JAX MFU loss CC-on vs CC-off; JAX
tokens/sec/chip CC-on vs off" — needs a measurement path, not just a
number: drive the REAL pipeline to ``off``, run each smoke workload, drive
it to ``on``, run them again, and report per-workload throughput/MFU deltas
in one JSON artifact.

On real CC-capable TPU hardware the delta captures the confidentiality
tax (encrypted HBM / IO paths); on this bench rig the device layer is the
fake, so the delta measures the harness's own noise floor — which is
exactly what CI asserts on (|delta| within noise on identical silicon).

Usage:
    python bench_ab.py [--workloads matmul,llama,resnet] [--cpu]
                       [--cycles 3 --reps 2] [--llama-size llama3.2-3b]

On-chip evidence runs want ≥5 samples per arm and interleaved cycles
(--cycles 3 --reps 2 → 6 alternating samples per arm): r4's reps=2
measured a negative loss — the noise floor exceeded the effect.

Prints exactly one JSON line:
    {"metric": "cc_on_off_mfu_loss_pct", "value": <worst-case loss %>,
     "ok": <worst loss <= 3%>, "workloads": {...per-workload detail...}}
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

# Primary throughput field per workload (the "tokens/sec/chip CC-on vs off"
# family from BASELINE.md).
THROUGHPUT_FIELD = {
    "matmul": "tflops",
    "llama": "tokens_per_sec",
    "resnet": "images_per_sec",
}


def _smoke_subprocess(
    workload: str, timeout_s: float, force_cpu: bool,
    extra_args: list[str] | None = None,
) -> dict:
    # Shared subprocess-smoke contract (tpu_cc_manager/smoke/runner.py);
    # imported lazily so the module parses before sys.path setup.
    from tpu_cc_manager.smoke.runner import run_workload_subprocess

    return run_workload_subprocess(
        workload, timeout_s=timeout_s, force_cpu=force_cpu,
        cwd=os.path.dirname(os.path.abspath(__file__)),
        extra_args=extra_args,
    )


def summarize_ab(
    workloads: list[str],
    samples: dict[str, dict[str, list]],
    detail: dict[str, dict[str, dict]],
    wall: dict[str, dict[str, float]],
    errors: dict[str, list[str]],
    retired: set[str],
    planned_reps: int,
    target_pct: float,
) -> dict:
    """Fold per-arm samples into the one-line A/B artifact.

    Contract (tested in tests/test_bench_select.py):
    - Per arm, the reported throughput/mfu/hbm triple is the median_low
      sample — one REAL measurement (even-count medians would otherwise
      average two runs into a number nobody observed).
    - ``loss_pct`` is positive when CC-on is slower (the confidentiality
      tax), computed off the medians; None when either arm has no
      accepted samples.
    - ``value`` is the WORST loss across workloads; ``ok`` requires at
      least one measured pair AND worst loss <= target — an A/B that
      measured nothing must not read as passing.
    - Accepted sample counts ride along (`reps` vs `planned_reps`) so
      shortfalls from retired/failed reps are visible in the artifact.
    """
    per_workload: dict[str, dict] = {}
    for w in workloads:
        field = THROUGHPUT_FIELD.get(w)
        per_workload[w] = {}
        for mode in ("off", "on"):
            got = samples[w][mode]
            med_i = (
                sorted(range(len(got)), key=lambda i: got[i][0])[
                    (len(got) - 1) // 2
                ]
                if got else None
            )
            med = got[med_i][0] if got else None
            last = detail[w].get(mode, {})
            per_workload[w][mode] = {
                "throughput_field": field,
                "throughput": med,
                "throughput_samples": [round(s[0], 2) for s in got],
                "mfu": got[med_i][1] if got else None,
                # Bandwidth-bound workloads (llama decode) report their
                # honest utilization here; None elsewhere.
                "hbm_bw_util": got[med_i][2] if got else None,
                "backend": last.get("backend"),
                "generation": last.get("generation"),
                "reps": len(got),
                "planned_reps": planned_reps,
                "wall_seconds": round(wall[w][mode], 2),
            }
        if errors[w]:
            per_workload[w]["errors"] = errors[w]
            per_workload[w]["retired_early"] = w in retired

    worst_loss_pct = 0.0
    measured_any = False
    for w, modes in per_workload.items():
        off_tp = (modes.get("off") or {}).get("throughput")
        on_tp = (modes.get("on") or {}).get("throughput")
        if off_tp and on_tp:
            measured_any = True
            loss_pct = round((off_tp - on_tp) / off_tp * 100.0, 2)
            modes["loss_pct"] = loss_pct
            worst_loss_pct = max(worst_loss_pct, loss_pct)
        else:
            modes["loss_pct"] = None

    value = round(worst_loss_pct, 2)
    return {
        "metric": "cc_on_off_mfu_loss_pct",
        "value": value,
        "unit": "%",
        "target": target_pct,
        # ok is judged on the REPORTED value so the artifact is
        # self-consistent (value <= target in the JSON must match ok).
        "ok": bool(measured_any and value <= target_pct),
        "workloads": per_workload,
    }


def drive_mode(mgr, kube, node: str, mode: str) -> None:
    from tpu_cc_manager.kubeclient.api import node_labels
    from tpu_cc_manager.labels import CC_MODE_STATE_LABEL

    ok = mgr.set_cc_mode(mode)
    state = node_labels(kube.get_node(node)).get(CC_MODE_STATE_LABEL)
    if not ok or state != mode:
        raise RuntimeError(f"pipeline did not converge to {mode!r} (state={state})")


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--workloads", default="matmul,llama",
        help="comma-separated smoke workloads to A/B (default: matmul,llama)",
    )
    parser.add_argument(
        "--cpu", action="store_true",
        help="pin the smokes to CPU (CI harness mode)",
    )
    parser.add_argument(
        "--timeout-s", type=float, default=300.0, help="per-smoke timeout",
    )
    parser.add_argument(
        "--reps", type=int, default=1,
        help="smoke repetitions per mode per cycle; the MEDIAN throughput "
        "across all samples of a mode is compared (raise when the "
        "backend's timing jitter exceeds the target — on the tunnel rig "
        "use >=5 total samples per mode)",
    )
    parser.add_argument(
        "--cycles", type=int, default=1,
        help="off/on transition cycles: each cycle re-drives the pipeline "
        "off then on and re-measures, interleaving the arms so a drift in "
        "the rig (thermal, tunnel latency) cannot masquerade as a CC tax",
    )
    parser.add_argument(
        "--llama-size", default=None, metavar="SIZE",
        help="llama config for the A/B (e.g. llama3.2-3b — the largest "
        "single-chip v5e fit; default: the smoke's backend default)",
    )
    parser.add_argument(
        "--batch", type=int, default=None,
        help="batch override passed to the llama/resnet smokes",
    )
    parser.add_argument(
        "--target-pct", type=float, default=3.0,
        help="max acceptable CC-on throughput loss %% (default: the 3%% "
        "north-star; CI's CPU harness run uses a larger value because CPU "
        "jitter is not the confidentiality tax)",
    )
    args = parser.parse_args()
    workloads = [w.strip() for w in args.workloads.split(",") if w.strip()]

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import logging

    logging.basicConfig(level=logging.WARNING)  # keep stdout to one JSON line

    from tpu_cc_manager.ccmanager.manager import CCManager
    from tpu_cc_manager.drain.pause import is_paused
    from tpu_cc_manager.kubeclient.api import node_labels
    from tpu_cc_manager.kubeclient.fake import FakeKube
    from tpu_cc_manager.labels import DRAIN_COMPONENT_LABELS, MODE_ON
    from tpu_cc_manager.tpudev.fake import FakeTpuBackend
    from tpu_cc_manager.utils.metrics import MetricsRegistry

    node, ns = "ab-node-0", "tpu-operator"
    kube = FakeKube()
    kube.add_node(node, {key: "true" for key in DRAIN_COMPONENT_LABELS})
    for key, app in DRAIN_COMPONENT_LABELS.items():
        kube.add_pod(ns, f"{app}-pod", node, labels={"app": app})

    def reactor(name, patched):
        for key, app in DRAIN_COMPONENT_LABELS.items():
            if is_paused(node_labels(patched).get(key)):
                kube.delete_pods_matching(ns, f"app={app}")

    kube.add_patch_reactor(reactor)

    # Start committed 'on' so driving to 'off' is a real transition (the
    # idempotent path would skip the pipeline entirely).
    backend = FakeTpuBackend(
        num_chips=4, accelerator_type="v5p-8", initial_mode=MODE_ON
    )
    mgr = CCManager(
        api=kube,
        backend=backend,
        node_name=node,
        operator_namespace=ns,
        evict_components=True,
        smoke_workload="none",  # smokes run below, once per workload per mode
        eviction_poll_interval_s=0.1,
        metrics=MetricsRegistry(),
    )

    extra_for = {w: [] for w in workloads}
    if args.llama_size and "llama" in extra_for:
        extra_for["llama"] += ["--size", args.llama_size]
    if args.batch is not None:
        for w in ("llama", "resnet"):
            if w in extra_for:
                extra_for[w] += ["--batch", str(args.batch)]

    # Interleaved arms: every cycle re-drives off then on through the real
    # pipeline and measures both, so samples of the two arms alternate in
    # time — rig drift (thermal, tunnel dispatch latency) averages into
    # BOTH arms instead of biasing whichever arm ran last. The median
    # across a mode's samples is compared (best-of rewards lucky outliers;
    # the median is what more reps actually stabilizes).
    from tpu_cc_manager.smoke.runner import SmokeError

    samples: dict[str, dict[str, list]] = {
        w: {"off": [], "on": []} for w in workloads
    }
    detail: dict[str, dict[str, dict]] = {w: {} for w in workloads}
    wall: dict[str, dict[str, float]] = {
        w: {"off": 0.0, "on": 0.0} for w in workloads
    }
    errors: dict[str, list[str]] = {w: [] for w in workloads}
    # A rep that dies (timeout, wedged tunnel, crash) must not discard the
    # samples already banked across earlier cycles — record the error and
    # keep going. But a DEAD backend makes every further rep cost the full
    # timeout, so a workload that fails twice in a row is retired for the
    # rest of the run; its arms report whatever was measured.
    MAX_CONSECUTIVE_FAILURES = 2
    retired: set[str] = set()
    consecutive_failures: dict[str, int] = {w: 0 for w in workloads}
    for _cycle in range(max(1, args.cycles)):
        for mode in ("off", "on"):
            drive_mode(mgr, kube, node, mode)
            for w in workloads:
                if w in retired:
                    continue
                t0 = time.perf_counter()
                field = THROUGHPUT_FIELD.get(w)
                for _ in range(max(1, args.reps)):
                    try:
                        result = _smoke_subprocess(
                            w, args.timeout_s, force_cpu=args.cpu,
                            extra_args=extra_for.get(w) or None,
                        )
                    except SmokeError as e:
                        errors[w].append(str(e))
                        consecutive_failures[w] += 1
                        if consecutive_failures[w] >= MAX_CONSECUTIVE_FAILURES:
                            retired.add(w)
                            break
                        continue
                    consecutive_failures[w] = 0
                    tp = result.get(field)
                    if tp:
                        samples[w][mode].append(
                            (tp, result.get("mfu"), result.get("hbm_bw_util"))
                        )
                    detail[w][mode] = result  # last full result per mode
                wall[w][mode] += time.perf_counter() - t0

    result = summarize_ab(
        workloads=workloads,
        samples=samples,
        detail=detail,
        wall=wall,
        errors=errors,
        retired=retired,
        planned_reps=max(1, args.reps) * max(1, args.cycles),
        target_pct=args.target_pct,
    )
    print(json.dumps(result))
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
