"""CC-on vs CC-off A/B harness for the ≤3% MFU-loss north-star.

BASELINE.md's second target — "≤ 3 % JAX MFU loss CC-on vs CC-off; JAX
tokens/sec/chip CC-on vs off" — needs a measurement path, not just a
number: drive the REAL pipeline to ``off``, run each smoke workload, drive
it to ``on``, run them again, and report per-workload throughput/MFU deltas
in one JSON artifact.

On real CC-capable TPU hardware the delta captures the confidentiality
tax (encrypted HBM / IO paths); on this bench rig the device layer is the
fake, so the delta measures the harness's own noise floor — which is
exactly what CI asserts on (|delta| within noise on identical silicon).

Usage:
    python bench_ab.py [--workloads matmul,llama,resnet] [--cpu]
                       [--cycles 3 --reps 5] [--llama-size llama3.2-3b]

Power: ≥5 samples per arm (the default is now reps=5) and interleaved
cycles (--cycles 3 --reps 2 → 6 alternating samples per arm): r4's
reps=2 measured a negative loss — the noise floor exceeded the effect.
The artifact reports mean ± 95% CI half-width per arm plus the
propagated loss half-width (`loss_pct_ci95_half_width`) and a
`loss_powered` verdict, so an underpowered delta is visible instead of
masquerading as a measurement.

Prints exactly one JSON line:
    {"metric": "cc_on_off_mfu_loss_pct", "value": <worst-case loss %>,
     "ok": <worst loss <= 3%>, "workloads": {...per-workload detail...}}
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

# Primary throughput field per workload (the "tokens/sec/chip CC-on vs off"
# family from BASELINE.md).
THROUGHPUT_FIELD = {
    "matmul": "tflops",
    "llama": "tokens_per_sec",
    "resnet": "images_per_sec",
}

# Two-sided 95% t critical values by degrees of freedom (n-1), through
# df=30 (the documented --cycles 3 --reps 5 recipe gives df=14 — falling
# back to the normal 1.96 there would shrink the interval ~9% and let
# loss_powered overclaim); beyond df=30 the normal 1.96 is within 2%.
# Small-n A/Bs must widen their interval — the r4 reps=2 run reported a
# negative "loss" precisely because two samples carry no power against
# the rig's noise floor (VERDICT miss #3).
_T95 = {1: 12.71, 2: 4.30, 3: 3.18, 4: 2.78, 5: 2.57,
        6: 2.45, 7: 2.36, 8: 2.31, 9: 2.26, 10: 2.23,
        11: 2.20, 12: 2.18, 13: 2.16, 14: 2.14, 15: 2.13,
        16: 2.12, 17: 2.11, 18: 2.10, 19: 2.09, 20: 2.09,
        21: 2.08, 22: 2.07, 23: 2.07, 24: 2.06, 25: 2.06,
        26: 2.06, 27: 2.05, 28: 2.05, 29: 2.05, 30: 2.04}


def mean_ci95(values: list[float]) -> tuple[float | None, float | None]:
    """(mean, 95% CI half-width) of a sample list; half-width is None
    below 2 samples (no variance estimate exists, and pretending ±0
    would be worse than saying so)."""
    if not values:
        return None, None
    n = len(values)
    mean = sum(values) / n
    if n < 2:
        return mean, None
    var = sum((v - mean) ** 2 for v in values) / (n - 1)
    t = _T95.get(n - 1, 1.96)
    return mean, t * (var ** 0.5) / (n ** 0.5)


def _smoke_subprocess(
    workload: str, timeout_s: float, force_cpu: bool,
    extra_args: list[str] | None = None,
) -> dict:
    # Shared subprocess-smoke contract (tpu_cc_manager/smoke/runner.py);
    # imported lazily so the module parses before sys.path setup.
    from tpu_cc_manager.smoke.runner import run_workload_subprocess

    return run_workload_subprocess(
        workload, timeout_s=timeout_s, force_cpu=force_cpu,
        cwd=os.path.dirname(os.path.abspath(__file__)),
        extra_args=extra_args,
    )


def summarize_ab(
    workloads: list[str],
    samples: dict[str, dict[str, list]],
    detail: dict[str, dict[str, dict]],
    wall: dict[str, dict[str, float]],
    errors: dict[str, list[str]],
    retired: set[str],
    planned_reps: int,
    target_pct: float,
) -> dict:
    """Fold per-arm samples into the one-line A/B artifact.

    Contract (tested in tests/test_bench_select.py):
    - Per arm, the reported throughput/mfu/hbm triple is the median_low
      sample — one REAL measurement (even-count medians would otherwise
      average two runs into a number nobody observed).
    - ``loss_pct`` is positive when CC-on is slower (the confidentiality
      tax), computed off the medians; None when either arm has no
      accepted samples.
    - ``value`` is the WORST loss across workloads; ``ok`` requires at
      least one measured pair AND worst loss <= target — an A/B that
      measured nothing must not read as passing.
    - Accepted sample counts ride along (`reps` vs `planned_reps`) so
      shortfalls from retired/failed reps are visible in the artifact.
    """
    per_workload: dict[str, dict] = {}
    for w in workloads:
        field = THROUGHPUT_FIELD.get(w)
        per_workload[w] = {}
        for mode in ("off", "on"):
            got = samples[w][mode]
            med_i = (
                sorted(range(len(got)), key=lambda i: got[i][0])[
                    (len(got) - 1) // 2
                ]
                if got else None
            )
            med = got[med_i][0] if got else None
            last = detail[w].get(mode, {})
            arm_mean, arm_ci = mean_ci95([s[0] for s in got])
            per_workload[w][mode] = {
                "throughput_field": field,
                "throughput": med,
                "throughput_samples": [round(s[0], 2) for s in got],
                # Mean ± 95% CI half-width: the power disclosure — a loss
                # smaller than the combined half-widths is inside the
                # noise floor, not a measured confidentiality tax.
                "mean": round(arm_mean, 2) if arm_mean is not None else None,
                "ci95_half_width": (
                    round(arm_ci, 2) if arm_ci is not None else None
                ),
                "mfu": got[med_i][1] if got else None,
                # Bandwidth-bound workloads (llama decode) report their
                # honest utilization here; None elsewhere.
                "hbm_bw_util": got[med_i][2] if got else None,
                "backend": last.get("backend"),
                "generation": last.get("generation"),
                "reps": len(got),
                "planned_reps": planned_reps,
                "wall_seconds": round(wall[w][mode], 2),
            }
        if errors[w]:
            per_workload[w]["errors"] = errors[w]
            per_workload[w]["retired_early"] = w in retired

    worst_loss_pct = 0.0
    measured_any = False
    for w, modes in per_workload.items():
        off_tp = (modes.get("off") or {}).get("throughput")
        on_tp = (modes.get("on") or {}).get("throughput")
        if off_tp and on_tp:
            measured_any = True
            loss_pct = round((off_tp - on_tp) / off_tp * 100.0, 2)
            modes["loss_pct"] = loss_pct
            worst_loss_pct = max(worst_loss_pct, loss_pct)
            # Propagated 95% half-width of the loss, in % points: the
            # two arms' CI half-widths combined in quadrature against
            # the off-arm mean. A reported |loss| below this value is
            # underpowered — more reps, not more digits.
            off_ci = (modes.get("off") or {}).get("ci95_half_width")
            on_ci = (modes.get("on") or {}).get("ci95_half_width")
            off_mean = (modes.get("off") or {}).get("mean")
            if off_ci is not None and on_ci is not None and off_mean:
                half = (off_ci ** 2 + on_ci ** 2) ** 0.5 / off_mean * 100.0
                modes["loss_pct_ci95_half_width"] = round(half, 2)
                modes["loss_powered"] = bool(abs(loss_pct) > half)
            else:
                modes["loss_pct_ci95_half_width"] = None
                modes["loss_powered"] = None
        else:
            modes["loss_pct"] = None

    value = round(worst_loss_pct, 2)
    return {
        "metric": "cc_on_off_mfu_loss_pct",
        "value": value,
        "unit": "%",
        "target": target_pct,
        # ok is judged on the REPORTED value so the artifact is
        # self-consistent (value <= target in the JSON must match ok).
        "ok": bool(measured_any and value <= target_pct),
        "workloads": per_workload,
    }


def drive_mode(mgr, kube, node: str, mode: str) -> None:
    from tpu_cc_manager.kubeclient.api import node_labels
    from tpu_cc_manager.labels import CC_MODE_STATE_LABEL

    ok = mgr.set_cc_mode(mode)
    state = node_labels(kube.get_node(node)).get(CC_MODE_STATE_LABEL)
    if not ok or state != mode:
        raise RuntimeError(f"pipeline did not converge to {mode!r} (state={state})")


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--workloads", default="matmul,llama",
        help="comma-separated smoke workloads to A/B (default: matmul,llama)",
    )
    parser.add_argument(
        "--cpu", action="store_true",
        help="pin the smokes to CPU (CI harness mode)",
    )
    parser.add_argument(
        "--timeout-s", type=float, default=300.0, help="per-smoke timeout",
    )
    parser.add_argument(
        "--reps", type=int, default=5,
        help="smoke repetitions per mode per cycle (default 5: the r4 "
        "reps=2 run sat below the rig's noise floor and measured a "
        "negative 'loss'; ≥5 samples per arm keep the CI half-width "
        "meaningful). The MEDIAN throughput across all samples of a mode "
        "is compared; the artifact reports mean ± 95% CI per arm and the "
        "propagated loss half-width",
    )
    parser.add_argument(
        "--cycles", type=int, default=1,
        help="off/on transition cycles: each cycle re-drives the pipeline "
        "off then on and re-measures, interleaving the arms so a drift in "
        "the rig (thermal, tunnel latency) cannot masquerade as a CC tax",
    )
    parser.add_argument(
        "--llama-size", default=None, metavar="SIZE",
        help="llama config for the A/B (e.g. llama3.2-3b — the largest "
        "single-chip v5e fit; default: the smoke's backend default)",
    )
    parser.add_argument(
        "--batch", type=int, default=None,
        help="batch override passed to the llama/resnet smokes",
    )
    parser.add_argument(
        "--target-pct", type=float, default=3.0,
        help="max acceptable CC-on throughput loss %% (default: the 3%% "
        "north-star; CI's CPU harness run uses a larger value because CPU "
        "jitter is not the confidentiality tax)",
    )
    args = parser.parse_args()
    workloads = [w.strip() for w in args.workloads.split(",") if w.strip()]
    planned_per_arm = max(1, args.reps) * max(1, args.cycles)
    if planned_per_arm < 5:
        print(
            f">>> WARNING: {planned_per_arm} sample(s) per arm is below "
            "the ~5-sample power floor (VERDICT miss #3: reps=2 measured "
            "a negative 'loss'); the artifact's loss_powered field will "
            "flag the shortfall",
            file=sys.stderr,
        )

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import logging

    logging.basicConfig(level=logging.WARNING)  # keep stdout to one JSON line

    from tpu_cc_manager.ccmanager.manager import CCManager
    from tpu_cc_manager.drain.pause import is_paused
    from tpu_cc_manager.kubeclient.api import node_labels
    from tpu_cc_manager.kubeclient.fake import FakeKube
    from tpu_cc_manager.labels import DRAIN_COMPONENT_LABELS, MODE_ON
    from tpu_cc_manager.tpudev.fake import FakeTpuBackend
    from tpu_cc_manager.utils.metrics import MetricsRegistry

    node, ns = "ab-node-0", "tpu-operator"
    kube = FakeKube()
    kube.add_node(node, {key: "true" for key in DRAIN_COMPONENT_LABELS})
    for key, app in DRAIN_COMPONENT_LABELS.items():
        kube.add_pod(ns, f"{app}-pod", node, labels={"app": app})

    def reactor(name, patched):
        for key, app in DRAIN_COMPONENT_LABELS.items():
            if is_paused(node_labels(patched).get(key)):
                kube.delete_pods_matching(ns, f"app={app}")

    kube.add_patch_reactor(reactor)

    # Start committed 'on' so driving to 'off' is a real transition (the
    # idempotent path would skip the pipeline entirely).
    backend = FakeTpuBackend(
        num_chips=4, accelerator_type="v5p-8", initial_mode=MODE_ON
    )
    mgr = CCManager(
        api=kube,
        backend=backend,
        node_name=node,
        operator_namespace=ns,
        evict_components=True,
        smoke_workload="none",  # smokes run below, once per workload per mode
        eviction_poll_interval_s=0.1,
        metrics=MetricsRegistry(),
    )

    extra_for = {w: [] for w in workloads}
    if args.llama_size and "llama" in extra_for:
        extra_for["llama"] += ["--size", args.llama_size]
    if args.batch is not None:
        for w in ("llama", "resnet"):
            if w in extra_for:
                extra_for[w] += ["--batch", str(args.batch)]

    # Interleaved arms: every cycle re-drives off then on through the real
    # pipeline and measures both, so samples of the two arms alternate in
    # time — rig drift (thermal, tunnel dispatch latency) averages into
    # BOTH arms instead of biasing whichever arm ran last. The median
    # across a mode's samples is compared (best-of rewards lucky outliers;
    # the median is what more reps actually stabilizes).
    from tpu_cc_manager.smoke.runner import SmokeError

    samples: dict[str, dict[str, list]] = {
        w: {"off": [], "on": []} for w in workloads
    }
    detail: dict[str, dict[str, dict]] = {w: {} for w in workloads}
    wall: dict[str, dict[str, float]] = {
        w: {"off": 0.0, "on": 0.0} for w in workloads
    }
    errors: dict[str, list[str]] = {w: [] for w in workloads}
    # A rep that dies (timeout, wedged tunnel, crash) must not discard the
    # samples already banked across earlier cycles — record the error and
    # keep going. But a DEAD backend makes every further rep cost the full
    # timeout, so a workload that fails twice in a row is retired for the
    # rest of the run; its arms report whatever was measured.
    MAX_CONSECUTIVE_FAILURES = 2
    retired: set[str] = set()
    consecutive_failures: dict[str, int] = {w: 0 for w in workloads}
    for _cycle in range(max(1, args.cycles)):
        for mode in ("off", "on"):
            drive_mode(mgr, kube, node, mode)
            for w in workloads:
                if w in retired:
                    continue
                t0 = time.perf_counter()
                field = THROUGHPUT_FIELD.get(w)
                for _ in range(max(1, args.reps)):
                    try:
                        result = _smoke_subprocess(
                            w, args.timeout_s, force_cpu=args.cpu,
                            extra_args=extra_for.get(w) or None,
                        )
                    except SmokeError as e:
                        errors[w].append(str(e))
                        consecutive_failures[w] += 1
                        if consecutive_failures[w] >= MAX_CONSECUTIVE_FAILURES:
                            retired.add(w)
                            break
                        continue
                    consecutive_failures[w] = 0
                    tp = result.get(field)
                    if tp:
                        samples[w][mode].append(
                            (tp, result.get("mfu"), result.get("hbm_bw_util"))
                        )
                    detail[w][mode] = result  # last full result per mode
                wall[w][mode] += time.perf_counter() - t0

    result = summarize_ab(
        workloads=workloads,
        samples=samples,
        detail=detail,
        wall=wall,
        errors=errors,
        retired=retired,
        planned_reps=max(1, args.reps) * max(1, args.cycles),
        target_pct=args.target_pct,
    )
    print(json.dumps(result))
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
