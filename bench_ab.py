"""CC-on vs CC-off A/B harness for the ≤3% MFU-loss north-star.

BASELINE.md's second target — "≤ 3 % JAX MFU loss CC-on vs CC-off; JAX
tokens/sec/chip CC-on vs off" — needs a measurement path, not just a
number: drive the REAL pipeline to ``off``, run each smoke workload, drive
it to ``on``, run them again, and report per-workload throughput/MFU deltas
in one JSON artifact.

On real CC-capable TPU hardware the delta captures the confidentiality
tax (encrypted HBM / IO paths); on this bench rig the device layer is the
fake, so the delta measures the harness's own noise floor — which is
exactly what CI asserts on (|delta| within noise on identical silicon).

Usage:
    python bench_ab.py [--workloads matmul,llama] [--cpu]

Prints exactly one JSON line:
    {"metric": "cc_on_off_mfu_loss_pct", "value": <worst-case loss %>,
     "ok": <worst loss <= 3%>, "workloads": {...per-workload detail...}}
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

# Primary throughput field per workload (the "tokens/sec/chip CC-on vs off"
# family from BASELINE.md).
THROUGHPUT_FIELD = {
    "matmul": "tflops",
    "llama": "tokens_per_sec",
    "resnet": "images_per_sec",
}


def _smoke_subprocess(workload: str, timeout_s: float, force_cpu: bool) -> dict:
    # Shared subprocess-smoke contract (tpu_cc_manager/smoke/runner.py);
    # imported lazily so the module parses before sys.path setup.
    from tpu_cc_manager.smoke.runner import run_workload_subprocess

    return run_workload_subprocess(
        workload, timeout_s=timeout_s, force_cpu=force_cpu,
        cwd=os.path.dirname(os.path.abspath(__file__)),
    )


def drive_mode(mgr, kube, node: str, mode: str) -> None:
    from tpu_cc_manager.kubeclient.api import node_labels
    from tpu_cc_manager.labels import CC_MODE_STATE_LABEL

    ok = mgr.set_cc_mode(mode)
    state = node_labels(kube.get_node(node)).get(CC_MODE_STATE_LABEL)
    if not ok or state != mode:
        raise RuntimeError(f"pipeline did not converge to {mode!r} (state={state})")


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--workloads", default="matmul,llama",
        help="comma-separated smoke workloads to A/B (default: matmul,llama)",
    )
    parser.add_argument(
        "--cpu", action="store_true",
        help="pin the smokes to CPU (CI harness mode)",
    )
    parser.add_argument(
        "--timeout-s", type=float, default=300.0, help="per-smoke timeout",
    )
    parser.add_argument(
        "--reps", type=int, default=1,
        help="smoke repetitions per mode; best-of throughput is compared "
        "(raise above 1 when the backend's timing jitter exceeds the target)",
    )
    parser.add_argument(
        "--target-pct", type=float, default=3.0,
        help="max acceptable CC-on throughput loss %% (default: the 3%% "
        "north-star; CI's CPU harness run uses a larger value because CPU "
        "jitter is not the confidentiality tax)",
    )
    args = parser.parse_args()
    workloads = [w.strip() for w in args.workloads.split(",") if w.strip()]

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import logging

    logging.basicConfig(level=logging.WARNING)  # keep stdout to one JSON line

    from tpu_cc_manager.ccmanager.manager import CCManager
    from tpu_cc_manager.drain.pause import is_paused
    from tpu_cc_manager.kubeclient.api import node_labels
    from tpu_cc_manager.kubeclient.fake import FakeKube
    from tpu_cc_manager.labels import DRAIN_COMPONENT_LABELS, MODE_ON
    from tpu_cc_manager.tpudev.fake import FakeTpuBackend
    from tpu_cc_manager.utils.metrics import MetricsRegistry

    node, ns = "ab-node-0", "tpu-operator"
    kube = FakeKube()
    kube.add_node(node, {key: "true" for key in DRAIN_COMPONENT_LABELS})
    for key, app in DRAIN_COMPONENT_LABELS.items():
        kube.add_pod(ns, f"{app}-pod", node, labels={"app": app})

    def reactor(name, patched):
        for key, app in DRAIN_COMPONENT_LABELS.items():
            if is_paused(node_labels(patched).get(key)):
                kube.delete_pods_matching(ns, f"app={app}")

    kube.add_patch_reactor(reactor)

    # Start committed 'on' so driving to 'off' is a real transition (the
    # idempotent path would skip the pipeline entirely).
    backend = FakeTpuBackend(
        num_chips=4, accelerator_type="v5p-8", initial_mode=MODE_ON
    )
    mgr = CCManager(
        api=kube,
        backend=backend,
        node_name=node,
        operator_namespace=ns,
        evict_components=True,
        smoke_workload="none",  # smokes run below, once per workload per mode
        eviction_poll_interval_s=0.1,
        metrics=MetricsRegistry(),
    )

    per_workload: dict[str, dict] = {w: {} for w in workloads}
    for mode in ("off", "on"):
        drive_mode(mgr, kube, node, mode)
        for w in workloads:
            t0 = time.perf_counter()
            field = THROUGHPUT_FIELD.get(w)
            best: dict = {}
            for _ in range(max(1, args.reps)):
                result = _smoke_subprocess(w, args.timeout_s, force_cpu=args.cpu)
                tp = result.get(field)
                if not best or (tp or 0) > (best.get(field) or 0):
                    best = result
            per_workload[w][mode] = {
                "throughput_field": field,
                "throughput": best.get(field),
                "mfu": best.get("mfu"),
                # Bandwidth-bound workloads (llama decode) report their
                # honest utilization here; None elsewhere.
                "hbm_bw_util": best.get("hbm_bw_util"),
                "backend": best.get("backend"),
                "generation": best.get("generation"),
                "reps": max(1, args.reps),
                "wall_seconds": round(time.perf_counter() - t0, 2),
            }

    worst_loss_pct = 0.0
    measured_any = False
    for w, modes in per_workload.items():
        off_tp = (modes.get("off") or {}).get("throughput")
        on_tp = (modes.get("on") or {}).get("throughput")
        if off_tp and on_tp:
            measured_any = True
            # Positive = CC-on is slower (the confidentiality tax).
            loss_pct = round((off_tp - on_tp) / off_tp * 100.0, 2)
            modes["loss_pct"] = loss_pct
            worst_loss_pct = max(worst_loss_pct, loss_pct)
        else:
            modes["loss_pct"] = None

    result = {
        "metric": "cc_on_off_mfu_loss_pct",
        "value": round(worst_loss_pct, 2),
        "unit": "%",
        "target": args.target_pct,
        "ok": bool(measured_any and worst_loss_pct <= args.target_pct),
        "workloads": per_workload,
    }
    print(json.dumps(result))
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
