# Version pins for the image build (reference analogue: versions.mk).
# Keep VERSION in lockstep with tpu_cc_manager/version.py.

VERSION := 0.3.0

PYTHON_VERSION := 3.12
JAX_VERSION := 0.9.0
BASE_DIST := gcr.io/distroless/python3-debian12:nonroot

REGISTRY ?= ghcr.io/tpu-cc-manager
IMAGE_NAME ?= tpu-cc-manager
